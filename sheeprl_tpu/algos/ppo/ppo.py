"""PPO, coupled training (capability parity with sheeprl/algos/ppo/ppo.py:106-452).

TPU-native structure:
- one controller process drives ``num_envs * world_size`` vectorized envs; "ranks" are
  mesh devices, so per-rank sizes keep their meaning as per-device shards;
- the act path is one jitted ``policy_step`` (the reference pays a per-step
  ``.cpu().numpy()`` sync, ppo.py:279-282 — here a single fused device program per
  vector step);
- GAE is a jitted ``lax.scan`` (reference: reversed Python loop, utils/utils.py:92-98);
- the optimization phase is a jitted minibatch step; under the ``dp`` strategy the
  minibatch is device_put with a ``data``-axis sharding and XLA inserts the gradient
  psum over ICI (replacing DDP allreduce at reference ppo.py:93).
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.agent import build_agent, policy_output
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import normalize_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import (
    ActPlacement,
    epoch_permutation,
    gae,
    normalize_tensor,
    polynomial_decay,
    save_configs,
)


def _build_optimizer(cfg, total_iters: int) -> optax.GradientTransformation:
    num_minibatches = max(
        1, (cfg.algo.rollout_steps * cfg.env.num_envs) // cfg.algo.per_rank_batch_size
    )
    lr = cfg.algo.optimizer.lr
    if cfg.algo.anneal_lr:
        lr = optax.linear_schedule(
            init_value=lr,
            end_value=0.0,
            transition_steps=total_iters * cfg.algo.update_epochs * num_minibatches,
        )
    tx = instantiate(cfg.algo.optimizer, lr=lr)
    if cfg.algo.max_grad_norm > 0.0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.algo.max_grad_norm), tx)
    return tx


def make_train_phase(
    agent,
    cfg,
    fabric,
    tx,
    actions_dim,
    is_continuous,
    cnn_keys,
    obs_keys,
    total_num_envs,
    state_shardings=None,
):
    """Build the fused per-iteration optimization program (GAE + update_epochs ×
    minibatches in one jitted scan). Module-level so the DP numerical-parity tests
    exercise exactly the program main() ships (reference train(), ppo.py:52-102).

    ``state_shardings`` — optional ``(params, opt_state, metrics)`` out_shardings
    pinning the state outputs on multi-device meshes (replicated on dp; without
    the pin GSPMD propagation may re-scatter small state leaves on output — the
    PR 8 residual; ``parallel/sharding.py build_state_shardings``)."""
    world_size = fabric.world_size
    loss_reduction = cfg.algo.loss_reduction
    vf_coef = float(cfg.algo.vf_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_advantages = bool(cfg.algo.normalize_advantages)
    global_bs = min(
        int(cfg.algo.per_rank_batch_size * world_size), int(cfg.algo.rollout_steps * total_num_envs)
    )
    num_rows = int(cfg.algo.rollout_steps * total_num_envs)
    num_minibatches = -(-num_rows // global_bs)  # ceil: partial minibatches pad-wrap
    share_data = bool(cfg.buffer.share_data)
    # static clip threshold for the learn-stats post-clip norms (the tx chains
    # clip_by_global_norm with exactly this value — _build_optimizer)
    max_grad_norm = float(cfg.algo.max_grad_norm or 0) or None
    # compile the Learn/* stats only when the telemetry learning plane is on
    learn_on = learn_stats.enabled(cfg)

    def loss_fn(params, batch, clip_coef, ent_coef):
        norm_obs = normalize_obs(batch, cnn_keys, obs_keys)
        actor_outs, new_values = agent.apply({"params": params}, norm_obs)
        out = policy_output(
            actor_outs, new_values, jax.random.PRNGKey(0), actions_dim, is_continuous, actions=batch["actions"]
        )
        advantages = batch["advantages"]
        if normalize_advantages:
            advantages = normalize_tensor(advantages)
        pg_loss = policy_loss(out["logprob"], batch["logprobs"], advantages, clip_coef, loss_reduction)
        v_loss = value_loss(
            out["values"], batch["values"], batch["returns"], clip_coef, clip_vloss, loss_reduction
        )
        ent_loss = entropy_loss(out["entropy"], loss_reduction)
        loss = pg_loss + vf_coef * v_loss + ent_coef * ent_loss
        # learn-stats aux (scalars only): value statistics, the value residual
        # vs the GAE return (the PPO analogue of a TD error), policy entropy
        stats = learn_stats.maybe(learn_on, lambda: {
            **learn_stats.value_stats(jax.lax.stop_gradient(out["values"])),
            **learn_stats.td_quantiles(jax.lax.stop_gradient(batch["returns"] - out["values"])),
            **learn_stats.entropy_stats(jax.lax.stop_gradient(out["entropy"])),
        })
        return loss, (pg_loss, v_loss, ent_loss, stats)

    jit_kwargs = {"out_shardings": tuple(state_shardings)} if state_shardings is not None else {}

    @partial(jax.jit, **jit_kwargs)
    def train_phase(params, opt_state, data, next_values, train_key, clip_coef, ent_coef):
        """One fused device program per iteration: GAE + update_epochs x minibatches."""
        returns, advantages = gae(
            data["rewards"],
            data["values"],
            data["dones"],
            next_values,
            cfg.algo.rollout_steps,
            cfg.algo.gamma,
            cfg.algo.gae_lambda,
        )
        # env-major flatten: the rollout arrives sharded on the env axis
        # (P(None, "data")), so flattening (T, E) -> (E*T) keeps each device's rows
        # as ONE contiguous block — the layout epoch_permutation's device-local
        # minibatching assumes. A time-major reshape would interleave shards.
        flat = {k: jnp.swapaxes(v, 0, 1).reshape(-1, *v.shape[2:]) for k, v in data.items()}
        flat["returns"] = jnp.swapaxes(returns, 0, 1).reshape(-1, 1)
        flat["advantages"] = jnp.swapaxes(advantages, 0, 1).reshape(-1, 1)
        if world_size > 1:
            flat = jax.lax.with_sharding_constraint(
                flat, jax.sharding.NamedSharding(fabric.mesh, jax.sharding.PartitionSpec("data"))
            )

        def epoch_body(carry, epoch_key):
            params, opt_state = carry
            perm = epoch_permutation(epoch_key, num_rows, world_size, share_data, global_bs)
            # pad (wrapping into the permutation) so every row is visited each epoch
            # even when num_rows is not a multiple of the global batch
            pad = num_minibatches * global_bs - num_rows
            if pad > 0:
                perm = jnp.concatenate([perm, perm[:pad]])
            mb_idx = perm[: num_minibatches * global_bs].reshape(num_minibatches, global_bs)

            def mb_body(carry, idx):
                params, opt_state = carry
                batch = {k: jnp.take(v, idx, axis=0) for k, v in flat.items()}
                grads, (pg, vl, ent, stats) = jax.grad(loss_fn, has_aux=True)(
                    params, batch, clip_coef, ent_coef
                )
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                learn = learn_stats.maybe(learn_on, lambda: {
                    **stats,
                    **learn_stats.group_stats(
                        "policy",
                        grads=grads,
                        updates=updates,
                        params=params,
                        opt_state=opt_state,
                        clip=max_grad_norm,
                    ),
                    "Learn/loss/policy": pg,
                    "Learn/loss/value": vl,
                    "Learn/loss/entropy": ent,
                })
                return (params, opt_state), (jnp.stack([pg, vl, ent]), learn)

            (params, opt_state), (losses, learn) = jax.lax.scan(mb_body, (params, opt_state), mb_idx)
            return (params, opt_state), (losses.mean(axis=0), learn)

        epoch_keys = jax.random.split(train_key, cfg.algo.update_epochs)
        (params, opt_state), (losses, learn) = jax.lax.scan(epoch_body, (params, opt_state), epoch_keys)
        mean_losses = losses.mean(axis=0)
        # learn is [epochs, minibatches]-stacked: reduce to window-ready scalars
        return params, opt_state, mean_losses, learn_stats.reduce_stacked(learn)

    return train_phase


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)

    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    # ranks = mesh devices: the controller drives num_envs * world_size envs
    total_num_envs = int(cfg.env.num_envs * world_size)
    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * total_num_envs + i,
                rank * total_num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(total_num_envs)
        ],
        # same-step autoreset restores the reference's gymnasium-0.x semantics: the
        # final observation of a done episode arrives in infos["final_obs"] and the
        # post-done row is a real reset transition, so truncation bootstrapping works
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN or MLP key for the encoder: "
            "`algo.cnn_keys.encoder=[rgb]` or `algo.mlp_keys.encoder=[state]`"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cfg.algo.cnn_keys.encoder)
        fabric.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
    cnn_keys = cfg.algo.cnn_keys.encoder

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state["agent"])

    # counters (semantics of reference ppo.py:216-231)
    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(total_num_envs * cfg.algo.rollout_steps)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size

    tx = _build_optimizer(cfg, total_iters)
    opt_state = tx.init(params)
    if state is not None and "optimizer" in state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["optimizer"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    if cfg.buffer.size < cfg.algo.rollout_steps:
        raise ValueError(
            f"The size of the buffer ({cfg.buffer.size}) cannot be lower "
            f"than the rollout steps ({cfg.algo.rollout_steps})"
        )
    rb = ReplayBuffer(
        cfg.buffer.size,
        total_num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=obs_keys,
    )

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    # ---------------- jitted programs ----------------
    # Latency design: the act path runs on the HOST CPU jax backend (microsecond
    # dispatch — envs are host-side anyway), the optimization phase is ONE jitted
    # device program per iteration (all epochs x minibatches fused via lax.scan), and
    # weights cross host<->device once per iteration. This replaces the reference's
    # per-step .cpu().numpy() syncs + per-minibatch optimizer steps (ppo.py:279-372).
    act = ActPlacement(fabric)
    act_on_cpu = act.on_cpu

    act_dim_total = int(np.sum(actions_dim))

    @partial(jax.jit, backend="cpu" if act_on_cpu else None)
    def policy_step_fn(params, obs: Dict[str, jax.Array], key):
        # the PRNG chain advances INSIDE the jitted program: an un-jitted
        # jax.random.split costs ~0.5 ms of host dispatch per env step, which alone
        # would halve throughput on the reference benchmark conditions
        key, step_key = jax.random.split(key)
        norm_obs = normalize_obs(obs, cnn_keys, obs_keys)
        norm_obs = {k: v.astype(jnp.float32) for k, v in norm_obs.items()}
        actor_outs, values = agent.apply({"params": params}, norm_obs)
        out = policy_output(actor_outs, values, step_key, actions_dim, is_continuous)
        if is_continuous:
            real_actions = out["actions"]
        else:
            split = jnp.split(out["actions"], np.cumsum(actions_dim)[:-1].tolist(), axis=-1)
            real_actions = jnp.stack([s.argmax(axis=-1) for s in split], axis=-1)
        # pack the per-step outputs into ONE array: the host pays a single
        # device->host conversion per step instead of three
        packed = jnp.concatenate(
            [out["values"], out["actions"], out["logprob"]], axis=-1
        ).astype(jnp.float32)
        return packed, real_actions, key

    @partial(jax.jit, backend="cpu" if act_on_cpu else None)
    def get_values(params, obs: Dict[str, jax.Array]):
        norm_obs = normalize_obs(obs, cnn_keys, obs_keys)
        norm_obs = {k: v.astype(jnp.float32) for k, v in norm_obs.items()}
        _, values = agent.apply({"params": params}, norm_obs)
        return values

    from sheeprl_tpu.parallel.sharding import build_state_shardings

    train_phase = make_train_phase(
        agent,
        cfg,
        fabric,
        tx,
        actions_dim,
        is_continuous,
        cnn_keys,
        obs_keys,
        total_num_envs,
        # extra_outputs=2: the losses vector AND the Learn/* stats block
        state_shardings=build_state_shardings(fabric, params, opt_state, extra_outputs=2),
    )

    # replicate params/opt_state over the mesh once; rollout data arrives data-sharded
    if world_size > 1:
        params = fabric.replicate_pytree(params)
        opt_state = fabric.replicate_pytree(opt_state)

    act_params = act.view(params)

    # ---------------- main loop ----------------
    ent_coef = initial_ent_coef
    clip_coef = initial_clip_coef

    # host-side PRNG chain lives on the CPU backend: splitting keys must never cost a
    # device roundtrip
    key = act.place(key)

    step_data: Dict[str, np.ndarray] = {}
    next_obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = next_obs[k][np.newaxis]

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/env_interaction_time"):
            for _ in range(cfg.algo.rollout_steps):
                policy_step += total_num_envs

                obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
                packed, real_actions, key = policy_step_fn(act_params, obs_host, key)
                real_actions_np = np.asarray(real_actions)
                if is_continuous:
                    env_actions = real_actions_np.reshape(envs.action_space.shape)
                else:
                    env_actions = real_actions_np.reshape(
                        (total_num_envs, -1) if is_multidiscrete else (total_num_envs,)
                    )

                obs, rewards, terminated, truncated, info = envs.step(env_actions)
                dones = np.logical_or(terminated, truncated).reshape(total_num_envs, 1).astype(np.float32)
                rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, 1)

                # truncation bootstrap (reference ppo.py:286-305)
                if "final_observation" in info or "final_obs" in info:
                    final_obs_arr = info.get("final_observation", info.get("final_obs"))
                    truncated_envs = np.nonzero(truncated)[0]
                    if len(truncated_envs) > 0:
                        real_next_obs = {
                            k: np.stack(
                                [np.asarray(final_obs_arr[i][k], dtype=np.float32) for i in truncated_envs]
                            )
                            for k in obs_keys
                        }
                        vals = np.asarray(get_values(act_params, real_next_obs)).reshape(
                            len(truncated_envs)
                        )
                        rewards[truncated_envs] += cfg.algo.gamma * vals.reshape(-1, 1)

                packed_np = np.asarray(packed)
                step_data["dones"] = dones[np.newaxis]
                step_data["values"] = packed_np[:, :1][np.newaxis]
                step_data["actions"] = packed_np[:, 1 : 1 + act_dim_total][np.newaxis]
                step_data["logprobs"] = packed_np[:, 1 + act_dim_total :][np.newaxis]
                step_data["rewards"] = rewards[np.newaxis]
                if cfg.buffer.memmap:
                    step_data["returns"] = np.zeros_like(rewards)[np.newaxis]
                    step_data["advantages"] = np.zeros_like(rewards)[np.newaxis]

                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                next_obs = obs
                for k in obs_keys:
                    step_data[k] = obs[k][np.newaxis]

                # under SAME_STEP autoreset the done-step infos arrive in final_info
                ep_info = info.get("final_info", info)
                if "episode" in ep_info:
                    ep = ep_info["episode"]
                    mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
                    rews, lens = ep["r"][mask], ep["l"][mask]
                    if len(rews) > 0:
                        telemetry.observe_episodes(rews, lens)
                        if aggregator and not aggregator.disabled:
                            aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                            aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        # bootstrap value for the last step
        obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
        next_values = np.asarray(get_values(act_params, obs_host))

        with timer("Time/train_time"):
            # single host->device upload of the whole rollout (sharded under dp)
            data = {k: np.asarray(rb[k]) for k in rb.buffer.keys() if k not in ("returns", "advantages")}
            if world_size > 1:
                data = jax.device_put(data, fabric.sharding(None, "data"))
            key, train_key = jax.random.split(key)
            # one-shot injected learning pathology (resilience.fault=lr_spike):
            # identity unless the fault armed this iteration
            params = apply_armed_learn_fault(params)
            params, opt_state, mean_losses, learn = train_phase(
                params, opt_state, data, next_values, np.asarray(train_key), clip_coef, ent_coef
            )
            telemetry.observe_train(1, mean_losses)
            telemetry.observe_learn(learn)
            if telemetry.wants_program("train_phase"):
                telemetry.register_program(
                    "train_phase",
                    train_phase,
                    (params, opt_state, data, next_values, np.asarray(train_key), clip_coef, ent_coef),
                    units=1,
                )
            if aggregator and not aggregator.disabled:
                losses_np = np.asarray(mean_losses)
                aggregator.update("Loss/policy_loss", losses_np[0])
                aggregator.update("Loss/value_loss", losses_np[1])
                aggregator.update("Loss/entropy_loss", losses_np[2])
            act_params = act.view(params)

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if "Time/train_time" in timers and timers["Time/train_time"] > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (policy_step - last_log) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if "Time/env_interaction_time" in timers and timers["Time/env_interaction_time"] > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (policy_step - last_log)
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step

        # anneal lr/clip/ent (reference ppo.py:414-424); lr anneal is an optax schedule
        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                iter_num, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                iter_num, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters, power=1.0
            )

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "optimizer": opt_state,
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            with timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(agent.apply, params, fabric, cfg, log_dir)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
