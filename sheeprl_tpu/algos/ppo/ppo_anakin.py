"""PPO, Anakin topology: on-device envs, rollout+GAE+optimization fused into
one donated jitted program over the mesh (see ``algos/ppo/anakin.py`` for the
architecture; ``algos/ppo/ppo.py`` is the host-env reference semantics)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.ppo.anakin import run_anakin
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    run_anakin(fabric, cfg)
