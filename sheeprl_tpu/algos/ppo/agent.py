"""PPO agent, Flax-native.

Capability parity with the reference agent (sheeprl/algos/ppo/agent.py:19-298):
multi-key CNN+MLP feature extraction, actor backbone with one head per discrete action
dimension (or a single mean/log-std head for continuous control), a critic MLP.

The reference's agent/player duality with tied weights (agent.py:254-298 +
get_single_device_fabric) collapses here: one Flax module definition, one params
pytree, and pure jitted functions for acting and training.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from sheeprl_tpu.models.models import MLP, MultiEncoder, NatureCNN
from sheeprl_tpu.utils.distribution import Independent, Normal, OneHotCategorical


class CNNEncoder(nn.Module):
    keys: Sequence[str]
    features_dim: int
    screen_size: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)  # channel-first input
        # flatten any frame-stack dim into channels
        if x.ndim >= 4 and x.shape[-4] > 1 and x.ndim > 4:
            x = jnp.reshape(x, (*x.shape[:-4], -1, *x.shape[-2:]))
        return NatureCNN(features_dim=self.features_dim, screen_size=self.screen_size, dtype=self.dtype)(x)


class MLPEncoder(nn.Module):
    keys: Sequence[str]
    features_dim: Optional[int]
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: Any = "relu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            output_dim=self.features_dim,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(x)


class PPOAgent(nn.Module):
    """Returns (actor_outs, values); heads follow the reference convention: continuous
    → a single head emitting concat(mean, log_std); discrete → one logits head per
    action dim."""

    actions_dim: Sequence[int]
    is_continuous: bool
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    screen_size: int
    encoder_cfg: Dict[str, Any]
    actor_cfg: Dict[str, Any]
    critic_cfg: Dict[str, Any]
    dtype: Any = jnp.float32

    def setup(self) -> None:
        cnn_encoder = (
            CNNEncoder(
                keys=self.cnn_keys,
                features_dim=self.encoder_cfg["cnn_features_dim"],
                screen_size=self.screen_size,
                dtype=self.dtype,
            )
            if len(self.cnn_keys) > 0
            else None
        )
        mlp_encoder = (
            MLPEncoder(
                keys=self.mlp_keys,
                features_dim=self.encoder_cfg["mlp_features_dim"],
                dense_units=self.encoder_cfg["dense_units"],
                mlp_layers=self.encoder_cfg["mlp_layers"],
                dense_act=self.encoder_cfg["dense_act"],
                layer_norm=self.encoder_cfg["layer_norm"],
                dtype=self.dtype,
            )
            if len(self.mlp_keys) > 0
            else None
        )
        self.feature_extractor = MultiEncoder(cnn_encoder, mlp_encoder)
        self.critic = MLP(
            hidden_sizes=(self.critic_cfg["dense_units"],) * self.critic_cfg["mlp_layers"],
            output_dim=1,
            activation=self.critic_cfg["dense_act"],
            layer_norm=self.critic_cfg["layer_norm"],
            dtype=self.dtype,
        )
        self.actor_backbone = MLP(
            hidden_sizes=(self.actor_cfg["dense_units"],) * self.actor_cfg["mlp_layers"],
            output_dim=None,
            activation=self.actor_cfg["dense_act"],
            layer_norm=self.actor_cfg["layer_norm"],
            dtype=self.dtype,
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(sum(self.actions_dim) * 2, dtype=self.dtype)]
        else:
            self.actor_heads = [nn.Dense(dim, dtype=self.dtype) for dim in self.actions_dim]

    def __call__(self, obs: Dict[str, jax.Array]) -> Tuple[List[jax.Array], jax.Array]:
        feat = self.feature_extractor(obs)
        pre = self.actor_backbone(feat)
        actor_outs = [head(pre) for head in self.actor_heads]
        values = self.critic(feat)
        return actor_outs, values


def make_dists(actor_outs: List[jax.Array], is_continuous: bool):
    """Build the per-head action distributions from raw actor outputs."""
    if is_continuous:
        mean, log_std = jnp.split(actor_outs[0], 2, axis=-1)
        return [Independent(Normal(mean, jnp.exp(log_std)), 1)]
    return [OneHotCategorical(logits=logits) for logits in actor_outs]


def policy_output(
    actor_outs: List[jax.Array],
    values: jax.Array,
    key: jax.Array,
    actions_dim: Sequence[int],
    is_continuous: bool,
    actions: Optional[jax.Array] = None,
    greedy: bool = False,
) -> Dict[str, jax.Array]:
    """Shared sample/evaluate path: samples (or re-evaluates given concatenated
    ``actions``) and returns dict(actions, logprob, entropy, values).

    ``actions`` follows the storage convention: a single concatenated array —
    continuous values, or per-dim one-hot blocks for discrete spaces.
    """
    dists = make_dists(actor_outs, is_continuous)
    if is_continuous:
        dist = dists[0]
        if actions is None:
            act = dist.mode if greedy else dist.sample(key)
        else:
            act = actions
        logprob = dist.log_prob(act)[..., None]
        entropy = dist.entropy()[..., None]
        return {"actions": act, "logprob": logprob, "entropy": entropy, "values": values}
    split_actions = None
    if actions is not None:
        import numpy as _np

        split_actions = jnp.split(actions, _np.cumsum(actions_dim)[:-1].tolist(), axis=-1)
    keys = jax.random.split(key, len(dists))
    sampled, logprobs, entropies = [], [], []
    for i, dist in enumerate(dists):
        if split_actions is None:
            a = dist.mode if greedy else dist.sample(keys[i])
        else:
            a = split_actions[i]
        sampled.append(a)
        logprobs.append(dist.log_prob(a))
        entropies.append(dist.entropy())
    return {
        "actions": jnp.concatenate(sampled, axis=-1),
        "logprob": jnp.stack(logprobs, axis=-1).sum(axis=-1, keepdims=True),
        "entropy": jnp.stack(entropies, axis=-1).sum(axis=-1, keepdims=True),
        "values": values,
    }


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
) -> Tuple[PPOAgent, Any]:
    """Create the module + initialized params (replaces the reference's
    build_agent/Fabric-wrapping dance, sheeprl/algos/ppo/agent.py:254-298)."""
    agent = PPOAgent(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        cnn_keys=tuple(cfg.algo.cnn_keys.encoder),
        mlp_keys=tuple(cfg.algo.mlp_keys.encoder),
        screen_size=cfg.env.screen_size,
        encoder_cfg=dict(cfg.algo.encoder),
        actor_cfg=dict(cfg.algo.actor),
        critic_cfg=dict(cfg.algo.critic),
        dtype=fabric.compute_dtype,
    )
    dummy_obs = {}
    for k in tuple(cfg.algo.cnn_keys.encoder):
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), dtype=jnp.float32)
    for k in tuple(cfg.algo.mlp_keys.encoder):
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), dtype=jnp.float32)
    params = agent.init(key, dummy_obs)["params"]
    return agent, params
