"""PPO, decoupled (actor–learner MPMD) training — capability parity with
sheeprl/algos/ppo/ppo_decoupled.py:33-670.

TPU-native topology: the reference splits torch ranks into a rank-0 player and a
trainer DDP group, moving data as pickled-object scatters and weights as a flattened
parameter broadcast. Here the split is **device-role based inside one controller
process**: the player runs on the host CPU backend (envs are host-side anyway) in
the main thread; the learner owns the accelerator mesh and runs in its own thread.
The two planes become explicit channels with the reference's blocking semantics:

- data plane  — a depth-1 queue of host rollout blocks (the reference's
  ``scatter_object_list`` of pickled chunks, ppo_decoupled.py:294-299); under dp the
  learner shards the block over the mesh ``data`` axis (the trainer-group DDP);
- weight plane — a depth-1 queue carrying the updated params pytree (the
  reference's flattened-parameter broadcast, ppo_decoupled.py:302-305): the player
  BLOCKS on it before the next rollout, preserving the synchronous alternation.

Under ``jax.distributed`` the same roles map onto N processes: process 0 is the
player (env host, local mesh); processes 1..N-1 form the LEARNER SLICE — one DP
mesh over all their devices (the reference's trainer DDP subgroup,
ppo_decoupled.py:645-666), every learner process running the same jitted train
program multi-controller-SPMD style. The data plane broadcasts the whole rollout
block to the slice and the block is then sharded over the slice's ``data`` axis —
a global reshuffle, strictly stronger than the reference's static N-1-chunk
scatter + Join for uneven shards."""

from __future__ import annotations

import os
import queue
import threading
from functools import partial
from typing import Any, Dict, Optional

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.agent import build_agent, policy_output
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import normalize_obs, space_actions_info, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import NullTelemetry, build_role_telemetry, build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, gae, normalize_tensor, polynomial_decay, save_configs


def _trainer_loop(
    fabric,
    cfg,
    agent,
    params,
    data_q: "queue.Queue",
    params_q: "queue.Queue",
    error: Dict[str, Any],
    geometry: Optional[Dict[str, int]] = None,
    resume_state: Optional[Dict[str, Any]] = None,
    telemetry=None,
    resilience=None,
):
    """Learner role (reference trainer(), ppo_decoupled.py:368-620): consume rollout
    blocks, run the fused epochs×minibatches program on the mesh, publish params.

    ``geometry`` overrides the rollout-derived sizes with the PLAYER's (two-process
    topology, where the roles may own different device counts); None derives them
    locally (threaded topology: both roles share one fabric). ``resume_state``
    restores params/optimizer/batch-size from a checkpoint (reference trainer
    resume, ppo_decoupled.py:406-437).

    ``telemetry``: the learner role's own stream (two-process topology only —
    the threaded trainer shares the player's process, whose telemetry already
    observes it; a second writer would also race the shared timer registry).
    ``resilience``: likewise the learner PROCESS's peer facade (heartbeats,
    rank-targeted faults, preempt-request publication, dead-peer aborts)."""
    from contextlib import nullcontext

    from sheeprl_tpu.resilience import NullResilience

    telemetry = telemetry if telemetry is not None else NullTelemetry()
    resilience = resilience if resilience is not None else NullResilience()
    train_span = timer("Time/train_time") if telemetry.enabled else nullcontext()
    try:
        world_size = fabric.world_size
        if geometry is not None:
            world_size = int(geometry["player_world_size"])
        if resume_state is not None:
            # derived from the CHECKPOINT, not cfg, so the thread-mode player's own
            # cfg override (same object) cannot double-divide
            cfg.algo.per_rank_batch_size = int(resume_state["batch_size"]) // world_size
            params = jax.tree_util.tree_map(jnp.asarray, resume_state["agent"])
        total_num_envs = int(cfg.env.num_envs * world_size)
        loss_reduction = cfg.algo.loss_reduction
        vf_coef = float(cfg.algo.vf_coef)
        clip_vloss = bool(cfg.algo.clip_vloss)
        normalize_advantages = bool(cfg.algo.normalize_advantages)
        global_bs = min(
            int(cfg.algo.per_rank_batch_size * world_size),
            int(cfg.algo.rollout_steps * total_num_envs),
        )
        num_rows = int(cfg.algo.rollout_steps * total_num_envs)
        num_minibatches = -(-num_rows // global_bs)
        is_continuous = agent.is_continuous
        actions_dim = agent.actions_dim
        cnn_keys = list(cfg.algo.cnn_keys.encoder)
        obs_keys = cnn_keys + list(cfg.algo.mlp_keys.encoder)

        policy_steps_per_iter = int(total_num_envs * cfg.algo.rollout_steps)
        total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
        from sheeprl_tpu.algos.ppo.ppo import _build_optimizer

        tx = _build_optimizer(cfg, total_iters)
        opt_state = tx.init(params)
        if resume_state is not None and resume_state.get("optimizer") is not None:
            opt_state = jax.tree_util.tree_map(jnp.asarray, resume_state["optimizer"])

        batch_sharding = None
        if fabric.world_size > 1 and global_bs % fabric.world_size == 0:
            batch_sharding = fabric.data_sharding
        # compile the Learn/* stats only when the telemetry learning plane is on
        learn_on = learn_stats.enabled(cfg)

        def loss_fn(params, batch, clip_coef, ent_coef):
            norm_obs = normalize_obs(batch, cnn_keys, obs_keys)
            actor_outs, new_values = agent.apply({"params": params}, norm_obs)
            out = policy_output(
                actor_outs, new_values, jax.random.PRNGKey(0), actions_dim, is_continuous,
                actions=batch["actions"],
            )
            advantages = batch["advantages"]
            if normalize_advantages:
                advantages = normalize_tensor(advantages)
            pg_loss = policy_loss(out["logprob"], batch["logprobs"], advantages, clip_coef, loss_reduction)
            v_loss = value_loss(
                out["values"], batch["values"], batch["returns"], clip_coef, clip_vloss, loss_reduction
            )
            ent_loss = entropy_loss(out["entropy"], loss_reduction)
            # learn-stats aux (scalars only): value statistics, value residual
            # vs the GAE return, policy entropy (utils/learn_stats.py)
            stats = learn_stats.maybe(learn_on, lambda: {
                **learn_stats.value_stats(jax.lax.stop_gradient(out["values"])),
                **learn_stats.td_quantiles(jax.lax.stop_gradient(batch["returns"] - out["values"])),
                **learn_stats.entropy_stats(jax.lax.stop_gradient(out["entropy"])),
            })
            return pg_loss + vf_coef * v_loss + ent_coef * ent_loss, (pg_loss, v_loss, ent_loss, stats)

        @jax.jit
        def train_phase(params, opt_state, flat, train_key, clip_coef, ent_coef):
            def epoch_body(carry, epoch_key):
                params, opt_state = carry
                perm = jax.random.permutation(epoch_key, num_rows)
                pad = num_minibatches * global_bs - num_rows
                if pad > 0:
                    perm = jnp.concatenate([perm, perm[:pad]])
                mb_idx = perm[: num_minibatches * global_bs].reshape(num_minibatches, global_bs)

                def mb_body(carry, idx):
                    params, opt_state = carry
                    batch = {k: jnp.take(v, idx, axis=0) for k, v in flat.items()}
                    if batch_sharding is not None:
                        # keep the gathered minibatch sharded over the learner mesh
                        # (XLA's propagation may otherwise replicate it, making the
                        # slice's DP redundant compute)
                        batch = jax.lax.with_sharding_constraint(batch, batch_sharding)
                    grads, (pg, vl, ent, stats) = jax.grad(loss_fn, has_aux=True)(
                        params, batch, clip_coef, ent_coef
                    )
                    updates, opt_state = tx.update(grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    learn = learn_stats.maybe(learn_on, lambda: {
                        **stats,
                        **learn_stats.group_stats(
                            "policy",
                            grads=grads,
                            updates=updates,
                            params=params,
                            opt_state=opt_state,
                            clip=float(cfg.algo.max_grad_norm or 0) or None,
                        ),
                        "Learn/loss/policy": pg,
                        "Learn/loss/value": vl,
                        "Learn/loss/entropy": ent,
                    })
                    return (params, opt_state), (jnp.stack([pg, vl, ent]), learn)

                (params, opt_state), (losses, learn) = jax.lax.scan(mb_body, (params, opt_state), mb_idx)
                return (params, opt_state), (losses.mean(axis=0), learn)

            epoch_keys = jax.random.split(train_key, cfg.algo.update_epochs)
            (params, opt_state), (losses, learn) = jax.lax.scan(epoch_body, (params, opt_state), epoch_keys)
            return params, opt_state, losses.mean(axis=0), learn_stats.reduce_stacked(learn)

        # sharding/replication follow the learner's OWN mesh, not the data geometry
        mesh_size = fabric.world_size
        if mesh_size > 1:
            params = fabric.replicate_pytree(params)
            opt_state = fabric.replicate_pytree(opt_state)

        key = jax.random.PRNGKey(cfg.seed + 1)
        rounds = 0
        while True:
            msg = data_q.get()
            if msg is None:  # sentinel (reference :344: scatter of -1)
                telemetry.close(rounds * policy_steps_per_iter)
                params_q.put(None)
                return
            flat, clip_coef, ent_coef, want_opt_state = msg
            with train_span:
                if mesh_size > 1:
                    # every learner process holds the full broadcast block, so this
                    # device_put forms the GLOBAL sharded array across the slice mesh
                    flat = jax.device_put(flat, fabric.data_sharding)
                key, train_key = jax.random.split(key)
                # one-shot injected learning pathology (resilience.fault=lr_spike
                # targeting the learner process): identity unless armed
                params = apply_armed_learn_fault(params)
                params, opt_state, mean_losses, learn = train_phase(
                    params, opt_state, flat, np.asarray(train_key), clip_coef, ent_coef
                )
                # weight plane: the player needs the full agent each round (it predicts
                # values during the rollout); opt_state only crosses when a checkpoint
                # is due. replicated_to_host handles the multi-process slice mesh, where
                # np.asarray refuses non-addressable (but replicated) outputs.
                # the Learn/* block rides host-side so the PLAYER's stream (the
                # run's primary) carries the learning window too
                reply = (
                    replicated_to_host(params),
                    replicated_to_host(opt_state) if want_opt_state else None,
                    replicated_to_host(mean_losses),
                    replicated_to_host(learn),
                )
            params_q.put(reply)
            rounds += 1
            telemetry.observe_train(1, reply[2])
            telemetry.observe_learn(reply[3])
            telemetry.step(rounds * policy_steps_per_iter)
            # publishes this rank's preempt request / heartbeat step and raises
            # RankFailureError on a declared-dead peer (never hang on one)
            resilience.step(rounds * policy_steps_per_iter)
    except BaseException as e:  # surface learner crashes to the player
        error["exc"] = e
        # out-of-band marker FIRST: on a non-src learner rank the channel put
        # below is a sequence-counter no-op (BroadcastChannel writes only on
        # src), so the marker is the only signal the blocked peers ever get
        _publish_channel_error(f"learner train loop failed: {e!r:.300}")
        # If the crash came from a channel collective the broadcast plane is
        # desynced — another lockstep put can block forever and bury the real
        # traceback. Only unblock the player while the channel is healthy.
        if not isinstance(e, _ChannelError):
            try:
                params_q.put(None)
            except _ChannelError:
                pass


from sheeprl_tpu.parallel.distributed import BroadcastChannel as _BcastChannel
from sheeprl_tpu.parallel.distributed import ChannelError as _ChannelError
from sheeprl_tpu.parallel.distributed import publish_channel_error as _publish_channel_error
from sheeprl_tpu.parallel.distributed import replicated_to_host


def _learner_process(fabric, cfg: Dict[str, Any]):
    """Learner role of the multi-process topology (reference trainer ranks,
    ppo_decoupled.py:368-620): one process of the learner SLICE, whose DP mesh
    spans every learner process's devices; consumes rollout blocks and publishes
    params over the host channels (all slice members run this same program)."""
    env = make_env(cfg, cfg.seed, 0, None, "learner")()
    observation_space = env.observation_space
    action_space = env.action_space
    env.close()
    is_continuous, is_multidiscrete, actions_dim = space_actions_info(action_space)
    # same seed as the player's rank-0 init -> identical initial params, so no
    # initial weight transfer is needed (the reference instead ships the first
    # flattened parameter vector, ppo_decoupled.py:126)
    key = fabric.seed_everything(cfg.seed)
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
    # the learner's peer facade comes up BEFORE the first blocking channel op:
    # its heartbeat lets the player distinguish "learner is compiling" from
    # "learner is dead", and its abort check breaks our own waits
    from sheeprl_tpu.parallel import distributed
    from sheeprl_tpu.resilience import channel_options

    telemetry = build_role_telemetry(
        fabric, cfg, "learner",
        rank=distributed.process_index(),
        leader=distributed.process_index() == 1,
    )
    resilience = build_resilience(fabric, cfg, None, telemetry=telemetry)
    opts = channel_options(cfg)
    data_q, params_q = _BcastChannel(src=0, **opts), _BcastChannel(src=1, **opts)
    # geometry handshake: the PLAYER's rollout shape drives the learner's minibatch
    # math — the two roles may own different device counts (env-hosts vs learner
    # slice), so deriving it from the learner's own world_size would corrupt
    # training (the reference likewise broadcasts cfg/agent args first, :114-117)
    geometry = data_q.get()
    if geometry is None:  # player failed before the first rollout
        params_q.put(None)  # pairs the player's cleanup ack-consume
        resilience.finalize()
        return
    resume_state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        try:
            resume_state = load_checkpoint(cfg.checkpoint.resume_from)
        except Exception as exc:
            # a load failure (path missing on this host, corrupt pickle) must
            # surface on the player's weight plane like any learner crash —
            # otherwise the player blocks on params_q.get until the channel
            # timeout with the real traceback buried here. The put is a real
            # write only on the params src rank; the KV marker covers the rest.
            _publish_channel_error(f"checkpoint resume load failed: {exc!r:.300}")
            try:
                params_q.put(None)
            except _ChannelError:
                pass
            raise
    error: Dict[str, Any] = {}
    try:
        _trainer_loop(
            fabric, cfg, agent, params, data_q, params_q, error, geometry=geometry,
            resume_state=resume_state, telemetry=telemetry, resilience=resilience,
        )
        if "exc" in error:
            # the player is (or will be) blocked sending its final sentinel — consume
            # it and ack so the lockstep broadcasts stay paired, then surface the crash.
            # Skip the pairing when the crash WAS the channel: its collectives are
            # desynced and would hang instead of pairing.
            if not isinstance(error["exc"], _ChannelError):
                try:
                    data_q.get()
                    params_q.put(None)
                except _ChannelError:
                    pass
            raise error["exc"]
    finally:
        resilience.finalize()


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    from sheeprl_tpu.parallel import distributed

    two_process = distributed.process_count() >= 2
    if two_process:
        # MPMD role split over jax.distributed processes: process 0 is the player
        # on its OWN devices; processes 1..N-1 are the learner slice sharing one DP
        # mesh (reference trainer subgroup, ppo_decoupled.py:645-666). The
        # data/weight planes ride the host object channel across all N.
        if distributed.process_index() >= 1:
            fabric.process_group = tuple(range(1, distributed.process_count()))
        fabric.local_mesh = True
        fabric._setup()
        if distributed.process_index() >= 1:
            return _learner_process(fabric, cfg)

    # Resume (reference ppo_decoupled.py:45-46,111-154): each role loads the
    # checkpoint from its own filesystem — the player (here, after the role
    # split, so learner processes don't pay a throwaway load) restores counters +
    # params; the learner slice restores params + optimizer state inside
    # _learner_process (same shared-path assumption as the reference's
    # fabric.load on all ranks).
    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        state = load_checkpoint(cfg.checkpoint.resume_from)

    # any player-side failure must release a learner blocked in a channel; the
    # KV-backed channels are STATEFUL (sequence counters), so the crash path must
    # reuse the live instances once they exist
    _protocol_done = False
    data_q: Any = None
    params_q: Any = None
    try:
        initial_ent_coef = float(cfg.algo.ent_coef)
        initial_clip_coef = float(cfg.algo.clip_coef)

        rank = fabric.global_rank
        world_size = fabric.world_size

        # two-process mode: the learner never calls get_log_dir, so sharing the dir over
        # a collective would desync the channel pairing — the player keeps it local
        log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, share=not two_process)
        logger = get_logger(fabric, cfg, log_dir=log_dir)
        fabric.logger = logger
        if logger is not None:
            logger.log_hyperparams(cfg.as_dict())
        fabric.print(f"Log dir: {log_dir}")
        telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
        resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

        total_num_envs = int(cfg.env.num_envs * world_size)
        vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
        envs = vectorized_env(
            [
                make_env(
                    cfg,
                    cfg.seed + rank * total_num_envs + i,
                    rank * total_num_envs,
                    log_dir if rank == 0 else None,
                    "train",
                    vector_env_idx=i,
                )
                for i in range(total_num_envs)
            ],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
        )
        observation_space = envs.single_observation_space
        if not isinstance(observation_space, gym.spaces.Dict):
            raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
        obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
        cnn_keys = cfg.algo.cnn_keys.encoder

        is_continuous, is_multidiscrete, actions_dim = space_actions_info(envs.single_action_space)

        key = fabric.seed_everything(cfg.seed + rank)
        key, agent_key = jax.random.split(key)
        agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
        if state is not None:
            params = jax.tree_util.tree_map(jnp.asarray, state["agent"])

        if fabric.is_global_zero:
            save_configs(cfg, log_dir)

        aggregator = None
        if not MetricAggregator.disabled:
            aggregator = instantiate(cfg.metric.aggregator)

        rb = ReplayBuffer(
            cfg.algo.rollout_steps,
            total_num_envs,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
            obs_keys=obs_keys,
        )

        policy_steps_per_iter = int(total_num_envs * cfg.algo.rollout_steps)
        total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
        # counters on resume: same semantics as the coupled path (ppo.py:219-226)
        start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
        policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state is not None else 0
        last_log = state["last_log"] if state is not None else 0
        last_checkpoint = state["last_checkpoint"] if state is not None else 0
        if state is not None:
            cfg.algo.per_rank_batch_size = state["batch_size"] // world_size

        # ---------------- channels + learner (thread or separate process) -----------
        error: Dict[str, Any] = {}
        if two_process:
            from sheeprl_tpu.resilience import channel_options

            opts = channel_options(cfg)
            data_q = _BcastChannel(src=0, **opts)
            params_q = _BcastChannel(src=1, **opts)
            trainer = None
            # geometry handshake, then the learner enters its data loop; a None releases
            # it if the player dies before the first rollout
            data_q.put({"player_world_size": world_size})
        else:
            data_q = queue.Queue(maxsize=1)
            params_q = queue.Queue(maxsize=1)
            trainer = threading.Thread(
                target=_trainer_loop,
                args=(fabric, cfg, agent, params, data_q, params_q, error),
                kwargs={"resume_state": state},
                daemon=True,
                name="ppo-learner",
            )
            trainer.start()

        act = ActPlacement(fabric)
        act_on_cpu = act.on_cpu

        @partial(jax.jit, backend="cpu" if act_on_cpu else None)
        def policy_step_fn(params, obs: Dict[str, jax.Array], key):
            # PRNG chain advances inside the jitted program (saves ~0.5 ms/step)
            key, step_key = jax.random.split(key)
            norm_obs = normalize_obs(obs, cnn_keys, obs_keys)
            norm_obs = {k: v.astype(jnp.float32) for k, v in norm_obs.items()}
            actor_outs, values = agent.apply({"params": params}, norm_obs)
            out = policy_output(actor_outs, values, step_key, actions_dim, is_continuous)
            if is_continuous:
                real_actions = out["actions"]
            else:
                split = jnp.split(out["actions"], np.cumsum(actions_dim)[:-1].tolist(), axis=-1)
                real_actions = jnp.stack([s.argmax(axis=-1) for s in split], axis=-1)
            return out, real_actions, key

        @partial(jax.jit, backend="cpu" if act_on_cpu else None)
        def get_values(params, obs: Dict[str, jax.Array]):
            norm_obs = normalize_obs(obs, cnn_keys, obs_keys)
            norm_obs = {k: v.astype(jnp.float32) for k, v in norm_obs.items()}
            _, values = agent.apply({"params": params}, norm_obs)
            return values

        @partial(jax.jit, backend="cpu" if act_on_cpu else None)
        def gae_fn(data, next_values):
            returns, advantages = gae(
                data["rewards"],
                data["values"],
                data["dones"],
                next_values,
                cfg.algo.rollout_steps,
                cfg.algo.gamma,
                cfg.algo.gae_lambda,
            )
            flat = {k: v.reshape(-1, *v.shape[2:]) for k, v in data.items()}
            flat["returns"] = returns.reshape(-1, 1)
            flat["advantages"] = advantages.reshape(-1, 1)
            return flat

        act_params = act.view(params)
        key = act.place(key)

        ent_coef = initial_ent_coef
        clip_coef = initial_clip_coef
        opt_state_host: Optional[Any] = None
        params_host = jax.tree_util.tree_map(np.asarray, params)

        step_data: Dict[str, np.ndarray] = {}
        next_obs = envs.reset(seed=cfg.seed)[0]
        for k in obs_keys:
            step_data[k] = next_obs[k][np.newaxis]

        for iter_num in range(start_iter, total_iters + 1):
            with timer("Time/env_interaction_time"):
                for _ in range(cfg.algo.rollout_steps):
                    policy_step += total_num_envs
                    obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
                    out, real_actions, key = policy_step_fn(act_params, obs_host, key)
                    real_actions_np = np.asarray(real_actions)
                    if is_continuous:
                        env_actions = real_actions_np.reshape(envs.action_space.shape)
                    else:
                        env_actions = real_actions_np.reshape(
                            (total_num_envs, -1) if is_multidiscrete else (total_num_envs,)
                        )

                    obs, rewards, terminated, truncated, info = envs.step(env_actions)
                    dones = np.logical_or(terminated, truncated).reshape(total_num_envs, 1).astype(np.float32)
                    rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, 1)

                    final_obs_arr = info.get("final_observation", info.get("final_obs"))
                    truncated_envs = np.nonzero(truncated)[0]
                    if final_obs_arr is not None and len(truncated_envs) > 0:
                        real_next_obs = {
                            k: np.stack(
                                [np.asarray(final_obs_arr[i][k], dtype=np.float32) for i in truncated_envs]
                            )
                            for k in obs_keys
                        }
                        vals = np.asarray(get_values(act_params, real_next_obs)).reshape(len(truncated_envs))
                        rewards[truncated_envs] += cfg.algo.gamma * vals.reshape(-1, 1)

                    step_data["dones"] = dones[np.newaxis]
                    step_data["values"] = np.asarray(out["values"], np.float32)[np.newaxis]
                    step_data["actions"] = np.asarray(out["actions"], np.float32)[np.newaxis]
                    step_data["logprobs"] = np.asarray(out["logprob"], np.float32)[np.newaxis]
                    step_data["rewards"] = rewards[np.newaxis]
                    rb.add(step_data, validate_args=cfg.buffer.validate_args)

                    next_obs = obs
                    for k in obs_keys:
                        step_data[k] = obs[k][np.newaxis]

                    ep_info = info.get("final_info", info)
                    if "episode" in ep_info:
                        ep = ep_info["episode"]
                        mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
                        rews, lens = ep["r"][mask], ep["l"][mask]
                        if len(rews) > 0:
                            telemetry.observe_episodes(rews, lens)
                            if aggregator and not aggregator.disabled:
                                aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                                aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

            # GAE on the player (reference ppo_decoupled.py:277-289), then ship the block
            obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
            next_values = np.asarray(get_values(act_params, obs_host))
            data = {k: np.asarray(rb[k]) for k in rb.buffer.keys()}
            flat = jax.tree_util.tree_map(np.asarray, gae_fn(data, next_values))

            # one preemption snapshot per iteration: the want_opt_state request,
            # the checkpoint block and the loop-exit break must agree on it (the
            # emergency checkpoint needs the opt state riding the weight plane)
            preempted = resilience.preempt_requested()

            with timer("Time/train_time"):
                # ask the learner for its opt_state only when this iteration will write a
                # checkpoint (the weight plane otherwise carries params alone)
                want_opt_state = (
                    (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
                    or cfg.dry_run
                    or (iter_num == total_iters and cfg.checkpoint.save_last)
                    or preempted
                )
                data_q.put((flat, clip_coef, ent_coef, want_opt_state))
                # weight plane: BLOCK until the learner finishes (reference :302)
                msg = params_q.get()
                if msg is None:
                    if "exc" in error:
                        raise error["exc"]
                    if two_process:
                        # a mid-run None on the weight plane is the remote learner's
                        # crash signal, not a clean shutdown
                        raise RuntimeError(
                            "the learner process crashed mid-run (sent a weight-plane "
                            "sentinel before the player finished); see its log"
                        )
                    break
                params_host, opt_state_host, mean_losses, learn = msg
                act_params = act.view(params_host)
                telemetry.observe_train(1, mean_losses)
                telemetry.observe_learn(learn)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Loss/policy_loss", float(mean_losses[0]))
                    aggregator.update("Loss/value_loss", float(mean_losses[1]))
                    aggregator.update("Loss/entropy_loss", float(mean_losses[2]))

            telemetry.step(policy_step)
            resilience.step(policy_step)
            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
            ):
                with timer("Time/logging_time"):
                    metrics_dict = aggregator.compute() if aggregator else {}
                    if logger is not None:
                        logger.log_metrics(metrics_dict, policy_step)
                        timers = timer.to_dict(reset=False)
                        if timers.get("Time/train_time", 0) > 0:
                            logger.log_metrics(
                                {"Time/sps_train": (policy_step - last_log) / max(timers["Time/train_time"], 1e-9)},
                                policy_step,
                            )
                        if timers.get("Time/env_interaction_time", 0) > 0:
                            logger.log_metrics(
                                {
                                    "Time/sps_env_interaction": (policy_step - last_log)
                                    / max(timers["Time/env_interaction_time"], 1e-9)
                                },
                                policy_step,
                            )
                    timer.to_dict(reset=True)
                    if aggregator:
                        aggregator.reset()
                last_log = policy_step

            if cfg.algo.anneal_clip_coef:
                clip_coef = polynomial_decay(
                    iter_num, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                ent_coef = polynomial_decay(
                    iter_num, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters, power=1.0
                )

            # a preemption forces an out-of-cadence emergency checkpoint through
            # the same callback path, then exits the loop; the sentinel below
            # forwards the shutdown to the trainer ranks over the data plane
            if (
                (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
                or cfg.dry_run
                or (iter_num == total_iters and cfg.checkpoint.save_last)
                or preempted
            ):
                last_checkpoint = policy_step
                ckpt_state = {
                    "agent": params_host,
                    "optimizer": opt_state_host,
                    "iter_num": iter_num * world_size,
                    "batch_size": cfg.algo.per_rank_batch_size * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                }
                ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
                with timer("Time/checkpoint_time"):
                    fabric.call(
                        "on_checkpoint_player",
                        ckpt_path=ckpt_path,
                        state=ckpt_state,
                    )
                resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
            if preempted:
                break

        # sentinel → learner exits (reference :344)
        data_q.put(None)
        if trainer is not None:
            trainer.join(timeout=60)
        else:
            # lockstep broadcast pairing: consume the learner's sentinel ack
            params_q.get()
        _protocol_done = True
        if "exc" in error:
            raise error["exc"]

        envs.close()
        # an in-flight async (orbax) checkpoint write must land before teardown
        wait_for_checkpoint()
        if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
            with timer("Time/test_time"):
                test(agent.apply, jax.tree_util.tree_map(jnp.asarray, act_params), fabric, cfg, log_dir)
        # closed AFTER the final test so the summary phases include eval time; an
        # exception path that skips this is flushed by cli.run_algorithm with
        # clean_exit=False
        telemetry.close(policy_step)
        if logger is not None:
            logger.finalize()
    except BaseException as e:
        # Best-effort learner release: send the data-plane sentinel, then consume
        # the learner's crash-path ack so its final broadcast is paired too. A crash
        # that WAS a channel collective (ChannelError) cannot be repaired from
        # here — the plane is desynced and another lockstep collective would hang,
        # not raise; the distributed runtime's failure detection is the backstop —
        # but every between-collectives crash point exits both roles.
        if two_process and not _protocol_done and not isinstance(e, _ChannelError):
            try:
                from sheeprl_tpu.resilience import channel_options

                # the channels are stateful: reuse the live instances when the
                # crash happened after their creation
                opts = channel_options(cfg)
                (data_q if data_q is not None else _BcastChannel(src=0, **opts)).put(None)
                (params_q if params_q is not None else _BcastChannel(src=1, **opts)).get()
            except Exception:
                pass
        raise
