"""PPO-family serving extractor (``get_serve_policy``, howto/serving.md).

Covers every algorithm that checkpoints a :class:`PPOAgent` params tree under
``state["agent"]``: ppo, ppo_decoupled, the Anakin fused topology, and a2c
(which reuses the PPO agent). Feedforward policies carry only their PRNG key as
per-session state; the serving carry is O(1) trivially.

Action parity with the evaluation path: with ``serve.greedy=true`` (the
default) the served action is the distribution mode — the exact computation of
``ppo.utils.test`` — so a served session's action stream matches the
sequential evaluate path bit-for-bit on identical observation sequences.
"""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import build_agent, policy_output
from sheeprl_tpu.serve.policy import ServePolicy, space_obs_spec
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_serve_policy


@register_serve_policy(algorithms=["ppo", "ppo_decoupled", "ppo_anakin", "a2c", "a2c_anakin"])
def get_serve_policy(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> ServePolicy:
    env = make_env(cfg, cfg.seed, 0, None, "serve-probe")()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    action_shape = tuple(int(s) for s in action_space.shape)
    env.close()

    agent, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, observation_space, jax.random.PRNGKey(cfg.seed)
    )
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state["agent"])

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    greedy = bool((cfg.get("serve") or {}).get("greedy", True))
    splits = np.cumsum(actions_dim)[:-1].tolist()

    def init_slot(params, key):
        return {"key": key}

    def step_slot(params, carry, obs):
        key, step_key = jax.random.split(carry["key"])
        norm: Dict[str, jax.Array] = {}
        for k in obs_keys:
            v = obs[k].astype(jnp.float32)
            if k in cnn_keys:
                # frame-stack dims fold into channels, pixels -> [-0.5, 0.5]
                # (the ppo.utils.prepare_obs/normalize_obs path, per slot)
                norm[k] = v.reshape(-1, *v.shape[-2:]) / 255.0 - 0.5
            else:
                norm[k] = v.reshape(-1)
        actor_outs, values = agent.apply({"params": params}, norm)
        out = policy_output(actor_outs, values, step_key, actions_dim, is_continuous, greedy=greedy)
        if is_continuous:
            env_action = out["actions"].reshape(action_shape).astype(jnp.float32)
        else:
            blocks = jnp.split(out["actions"], splits, axis=-1)
            env_action = jnp.stack([b.argmax(axis=-1) for b in blocks], axis=-1).reshape(
                action_shape
            ).astype(jnp.int32)
        return env_action, {"key": key}

    return ServePolicy(
        algo=str(cfg.algo.name),
        params=params,
        init_slot=init_slot,
        step_slot=step_slot,
        obs_spec=space_obs_spec(observation_space, obs_keys),
        action_shape=action_shape,
        action_dtype=np.float32 if is_continuous else np.int32,
        meta={"family": "ppo", "greedy": greedy, "recurrent": False},
    )
