"""PPO helpers: metric whitelist, obs preparation, greedy test rollout
(reference: sheeprl/algos/ppo/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {"Rewards/rew_avg", "Game/ep_len_avg", "Loss/value_loss", "Loss/policy_loss", "Loss/entropy_loss"}
MODELS_TO_REGISTER = {"agent"}


def normalize_obs(
    obs: Dict[str, Any], cnn_keys: Sequence[str], obs_keys: Sequence[str]
) -> Dict[str, Any]:
    """Pixels → [-0.5, 0.5]; vectors pass through (reference utils.py:normalize_obs)."""
    return {k: obs[k] / 255.0 - 0.5 if k in cnn_keys else obs[k] for k in obs_keys}


def prepare_obs(
    fabric, obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), num_envs: int = 1, **_: Any
) -> Dict[str, jax.Array]:
    """Host obs dict → normalized float device arrays shaped [num_envs, ...]."""
    out = {}
    for k in obs.keys():
        v = np.asarray(obs[k], dtype=np.float32)
        if k in cnn_keys:
            v = v.reshape(num_envs, -1, *v.shape[-2:])
        else:
            v = v.reshape(num_envs, -1)
        out[k] = jnp.asarray(v)
    return normalize_obs(out, cnn_keys, list(obs.keys()))


def test(agent_apply, params, fabric, cfg, log_dir: str) -> None:
    """Greedy single-env rollout logging Test/cumulative_reward
    (reference utils.py:test)."""
    from sheeprl_tpu.algos.ppo.agent import policy_output
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, None, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    key = jax.random.PRNGKey(cfg.seed)
    while not done:
        jobs = prepare_obs(fabric, obs, cnn_keys=cfg.algo.cnn_keys.encoder)
        actor_outs, values = agent_apply({"params": params}, jobs)
        key, sub = jax.random.split(key)
        out = policy_output(
            actor_outs, values, sub, agent_actions_dim(cfg, env), is_continuous(env), greedy=True
        )
        actions = np.asarray(out["actions"])
        if is_continuous(env):
            real_actions = actions.reshape(env.action_space.shape)
        else:
            dims = agent_actions_dim(cfg, env)
            split = np.split(actions, np.cumsum(dims)[:-1].tolist(), axis=-1)
            real_actions = np.concatenate([s.argmax(axis=-1) for s in split], axis=-1).reshape(
                env.action_space.shape
            )
        obs, reward, terminated, truncated, _ = env.step(real_actions)
        done = bool(terminated) or bool(truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None):
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()


def is_continuous(env) -> bool:
    import gymnasium as gym

    return isinstance(env.action_space, gym.spaces.Box)


def agent_actions_dim(cfg, env) -> Sequence[int]:
    import gymnasium as gym

    space = env.action_space
    if isinstance(space, gym.spaces.Box):
        return list(space.shape)
    if isinstance(space, gym.spaces.MultiDiscrete):
        return space.nvec.tolist()
    return [space.n]


def space_actions_info(action_space):
    """(is_continuous, is_multidiscrete, actions_dim) for a single action space —
    shared by the player and learner roles so their agents derive identical shapes
    (the no-initial-weight-transfer design relies on identical init)."""
    import gymnasium as gym

    cont = isinstance(action_space, gym.spaces.Box)
    multi = isinstance(action_space, gym.spaces.MultiDiscrete)
    dims = tuple(
        action_space.shape if cont else (action_space.nvec.tolist() if multi else [action_space.n])
    )
    return cont, multi, dims
