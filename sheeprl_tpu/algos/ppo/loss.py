"""PPO losses (reference: sheeprl/algos/ppo/loss.py:1-72), jnp-native."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return x.mean()
    if reduction == "sum":
        return x.sum()
    if reduction == "none":
        return x
    raise ValueError(f"unknown reduction {reduction!r}")


def policy_loss(
    new_logprobs: jax.Array,
    old_logprobs: jax.Array,
    advantages: jax.Array,
    clip_coef: float,
    reduction: str = "mean",
) -> jax.Array:
    """Clipped-surrogate objective."""
    logratio = new_logprobs - old_logprobs
    ratio = jnp.exp(logratio)
    pg_loss1 = -advantages * ratio
    pg_loss2 = -advantages * jnp.clip(ratio, 1 - clip_coef, 1 + clip_coef)
    return _reduce(jnp.maximum(pg_loss1, pg_loss2), reduction)


def value_loss(
    new_values: jax.Array,
    old_values: jax.Array,
    returns: jax.Array,
    clip_coef: float,
    clip_vloss: bool,
    reduction: str = "mean",
) -> jax.Array:
    """MSE on the (optionally clipped) value prediction — exact reference semantics
    (sheeprl/algos/ppo/loss.py:44-58: no 0.5 factor, clipped path uses the clipped
    prediction only)."""
    if clip_vloss:
        values_pred = old_values + jnp.clip(new_values - old_values, -clip_coef, clip_coef)
    else:
        values_pred = new_values
    return _reduce(jnp.square(values_pred - returns), reduction)


def entropy_loss(entropy: jax.Array, reduction: str = "mean") -> jax.Array:
    return -_reduce(entropy, reduction)
