"""SAC losses (reference sheeprl/algos/sac/loss.py:1-26)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def critic_loss(qf_values: jax.Array, next_qf_value: jax.Array, num_critics: int) -> jax.Array:
    """Sum of per-critic MSEs against the shared soft target; qf_values is
    ``[batch, n]``, next_qf_value ``[batch, 1]``."""
    return jnp.sum(
        jnp.stack([jnp.mean((qf_values[..., i : i + 1] - next_qf_value) ** 2) for i in range(num_critics)])
    )


def policy_loss(alpha: jax.Array, logprobs: jax.Array, min_qf_values: jax.Array) -> jax.Array:
    return jnp.mean(alpha * logprobs - min_qf_values)


def entropy_loss(log_alpha: jax.Array, logprobs: jax.Array, target_entropy: jax.Array) -> jax.Array:
    return jnp.mean(-log_alpha * (logprobs + target_entropy))
