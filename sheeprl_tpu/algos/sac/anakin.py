"""SAC Anakin topology: rollout + replay ring + N gradient steps in ONE program.

PR 7's Anakin port (``algos/ppo/anakin.py``, Podracer arxiv 2104.06272) fused
the on-policy loop; every off-policy loop still pays the host↔device boundary
*twice* per iteration — a numpy replay add per env step and a sampled-batch
upload per train round. This module closes that gap with the device-resident
replay ring (``data/device_ring.py``): environments (``envs/jax`` plane), ring
write, uniform ring sample (Feistel ``utils/prp.py``) and the full
``lax.scan``-ed gradient phase (the UNJITTED :func:`~sheeprl_tpu.algos.sac.sac.
make_train_body` — the same update every SAC topology runs) compile into ONE
donated XLA program over the mesh. Steady-state host traffic is the Anakin
contract: opaque device references carried in a Python loop, a handful of
scalars pulled at telemetry cadence, zero callbacks/infeeds/outfeeds — proven
off-chip by the ``sac.anakin_step`` entry in ``analysis/programs.py``
(``sheeprl.py lint --aot``).

Differences from the host loop (``algos/sac/sac.py``), documented in
``howto/device_replay.md``:

- ``buffer.backend=device`` is REQUIRED: the ring is the replay storage; a host
  ``ReplayBuffer`` exists only as the checkpoint-durability twin
  (``DeviceRingSampler.sync_to_host`` at checkpoint cadence, ``device_put``
  back on resume — ring contents and counters round-trip exactly).
- the replay-ratio governor is STATIC: ``G = round(algo.replay_ratio *
  rollout_steps * num_envs / world_size)`` gradient steps are compiled into the
  program (a host-side ``Ratio`` would need a per-iteration recompile).
- ``algo.learning_starts`` is ignored: the first fused iteration already writes
  ``rollout_steps * num_envs`` fresh transitions before its sample phase, and
  the ring samples uniformly over the valid region from the first row.

Distribution mirrors the PPO Anakin mesh: envs and ring sharded over ``data``
(the ring's batch axis is the env axis), params/opt-state replicated, XLA
inserting the gradient all-reduce; ``build_state_shardings``-derived
``out_shardings`` pin the carried state so GSPMD propagation can never
re-scatter a donated leaf between iterations.
"""

from __future__ import annotations

import os
import time
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.anakin import _measure_rollout_seconds
from sheeprl_tpu.algos.sac.agent import build_agent, squash_and_logprob
from sheeprl_tpu.algos.sac.sac import build_optimizers, init_opt_state, make_train_body
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.analysis.programs import register_fused_program
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.device_ring import ring_capacity, ring_init, ring_sample, ring_write
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.envs.jax import make_jax_env
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import BenchWindow, packed_device_get, save_configs

# stats accumulator keys carried device-side across iterations (pulled + zeroed
# at the logging cadence; ``losses`` is overwritten each call, not accumulated)
_STATS_ACC = ("ep_return_sum", "ep_length_sum", "ep_count")

# the transition schema one rollout step appends to the ring; ``terminated``
# AND ``truncated`` are both stored so the checkpoint snapshot satisfies the
# host buffer's ``_ckpt_rb`` durability protocol unchanged
RING_ROW_KEYS = (
    "observations",
    "next_observations",
    "actions",
    "rewards",
    "terminated",
    "truncated",
)


def ring_row_specs(obs_dim: int, act_dim: int):
    """Per-env trailing (shape, dtype) of each ring row key — ONE schema shared
    by the driver's ``ring_init`` and the AOT builder."""
    return {
        "observations": ((int(obs_dim),), np.float32),
        "next_observations": ((int(obs_dim),), np.float32),
        "actions": ((int(act_dim),), np.float32),
        "rewards": ((1,), np.float32),
        "terminated": ((1,), np.float32),
        "truncated": ((1,), np.float32),
    }


def grad_steps_per_iteration(cfg, total_num_envs: int, world_size: int) -> int:
    """The STATIC per-rank gradient-step count of one fused iteration: the
    replay-ratio contract (``algo.replay_ratio`` gradient steps per policy
    step, reference sac.py:301-309) applied to the iteration's
    ``rollout_steps * num_envs`` policy steps and baked into the program."""
    T = int(cfg.algo.rollout_steps)
    return max(1, int(round(float(cfg.algo.replay_ratio) * T * total_num_envs / world_size)))


def make_sac_anakin_program(actor, critic, env, cfg, fabric, txs, total_num_envs, params, opt_state):
    """Build (sac_anakin_step, rollout_only, grad_steps_per_iter).

    ``sac_anakin_step(params, opt_state, env_state, obs, ring, key, stats,
    iter_num) -> (params, opt_state, env_state, obs, ring, key, stats, learn)``
    is the fused per-iteration program — T env+act steps, ring write, ring
    sample, G gradient steps — jitted with every carried tree donated (stats is
    NOT donated: telemetry holds the losses reference across calls, exactly the
    PPO Anakin convention). ``rollout_only`` is a jit of just the acting half
    for the measured rollout/train phase split.

    Module-level so the ``sac.anakin_step`` AOT registration lowers exactly the
    program the driver runs. ``params``/``opt_state`` are consumed only to
    derive the multi-device ``out_shardings`` pin.
    """
    world_size = fabric.world_size
    T = int(cfg.algo.rollout_steps)
    B = int(cfg.algo.per_rank_batch_size) * world_size
    G = grad_steps_per_iteration(cfg, total_num_envs, world_size)
    act_dim = int(np.prod(env.spec.action.shape))
    action_scale = jnp.asarray(actor.action_scale, dtype=jnp.float32)
    action_bias = jnp.asarray(actor.action_bias, dtype=jnp.float32)
    target_entropy = -float(act_dim)

    data_sharding = fabric.sharding("data") if world_size > 1 else None
    # ring storage is [capacity, n_envs, ...]: the env axis (axis 1) carries the
    # mesh's data split, matching the rollout's env sharding so the write is a
    # purely local scatter on every device
    ring_data_sharding = fabric.sharding(None, "data") if world_size > 1 else None
    batch_sharding = fabric.sharding(None, "data") if world_size > 1 else None

    # ONE update implementation for every SAC topology: the host loop jits this
    # same body standalone (make_train_phase); here it fuses after the ring
    train_body = make_train_body(
        cfg, actor, critic, target_entropy, policy_steps_per_iter=T * total_num_envs, txs=txs
    )

    def rollout_phase(params, env_state, obs, key):
        """T fused env+act steps; returns the new env carry, the [T, E, ...]
        ring rows and the summed episode stats of episodes that ended."""

        def body(carry, _):
            env_state, obs, key = carry
            key, step_key = jax.random.split(key)
            fobs = obs.astype(jnp.float32)
            mean, std = actor.apply({"params": params["actor"]}, fobs)
            actions, _ = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
            env_state, next_obs, reward, done, info = env.step(env_state, actions)
            done_f = done.astype(jnp.float32)
            transition = {
                "observations": fobs,
                # the PRE-reset observation of this step — the true successor
                # state (the host loop's real_next_obs assembly, sac.py:281-289)
                "next_observations": info["terminal_observation"].astype(jnp.float32),
                "actions": actions,
                "rewards": reward[:, None].astype(jnp.float32),
                "terminated": info["terminated"].astype(jnp.float32)[:, None],
                "truncated": info["truncated"].astype(jnp.float32)[:, None],
            }
            step_stats = jnp.stack(
                [
                    jnp.sum(info["episode_return"] * done_f),
                    jnp.sum(info["episode_length"].astype(jnp.float32) * done_f),
                    jnp.sum(done_f),
                ]
            )
            return (env_state, next_obs, key), (transition, step_stats)

        (env_state, obs, key), (traj, step_stats) = jax.lax.scan(
            body, (env_state, obs, key), None, length=T
        )
        return env_state, obs, key, traj, step_stats.sum(axis=0)

    def sac_anakin_step(params, opt_state, env_state, obs, ring, key, stats, iter_num):
        if data_sharding is not None:
            env_state = jax.lax.with_sharding_constraint(env_state, data_sharding)
            obs = jax.lax.with_sharding_constraint(obs, data_sharding)
            ring = dict(
                ring, data=jax.lax.with_sharding_constraint(ring["data"], ring_data_sharding)
            )
        env_state, obs, key, traj, ep_stats = rollout_phase(params, env_state, obs, key)
        ring = ring_write(ring, traj)
        key, sample_key, train_key = jax.random.split(key, 3)
        batch = ring_sample(ring, sample_key, batch_size=B, n_samples=G)
        if batch_sharding is not None:
            batch = jax.lax.with_sharding_constraint(batch, batch_sharding)
        params, opt_state, losses, learn = train_body(
            params, opt_state, batch, iter_num, train_key
        )
        new_stats = {
            "ep_return_sum": stats["ep_return_sum"] + ep_stats[0],
            "ep_length_sum": stats["ep_length_sum"] + ep_stats[1],
            "ep_count": stats["ep_count"] + ep_stats[2],
            "losses": losses,
        }
        return params, opt_state, env_state, obs, ring, key, new_stats, learn

    jit_kwargs: Dict[str, Any] = {}
    if fabric.num_devices > 1:
        # pin the carried outputs (PR 8's build_state_shardings rationale): the
        # train state replicated, the ring env-sharded, key/stats replicated;
        # env_state/learn propagate from the internal constraints (env-state
        # pytree and Learn/* block structures are only known at trace time —
        # None leaves in out_shardings mean "GSPMD decides" for that subtree)
        replicated = fabric.replicated
        jit_kwargs["out_shardings"] = (
            fabric.param_shardings(params),
            fabric.param_shardings(opt_state),
            None,  # env_state: data-sharded via the in-program constraint
            data_sharding,
            {"data": ring_data_sharding, "pos": replicated, "fill": replicated},
            replicated,
            {k: replicated for k in (*_STATS_ACC, "losses")},
            None,  # Learn/* stats block
        )
    fused = jax.jit(sac_anakin_step, donate_argnums=(0, 1, 2, 3, 4, 5), **jit_kwargs)
    rollout_only = jax.jit(rollout_phase)
    return fused, rollout_only, G


@register_fused_program(
    "sac.anakin_step",
    min_donated=8,
    expect_collectives=("all-reduce",),
    compile_on_cpu=True,
    devices=8,
    doc="fused SAC rollout + device replay ring + G gradient steps on the 8-device dp mesh",
)
def _aot_sac_anakin_program():
    """The fused off-policy program on the 8-device CPU mesh: donation must
    survive for every carried tree (params/opt-state/env-state/obs/RING/key),
    the steady state must carry NO host callbacks/outfeeds — the replay path
    included, which is the whole point of the device ring — and the dp gradient
    psum must appear as an all-reduce in the optimized HLO."""
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.data.device_ring import ring_init
    from sheeprl_tpu.parallel.fabric import Fabric

    devices = 8
    cfg = compose(
        [
            "exp=sac_anakin_benchmarks",
            "fabric.accelerator=cpu",
            f"fabric.devices={devices}",
            "fabric.strategy=dp",
            "env.num_envs=16",
            "algo.rollout_steps=8",
            "algo.per_rank_batch_size=32",
            "algo.replay_ratio=0.02",
            "buffer.size=4096",
            # lower the GROWN program (Learn/* stats compile in under telemetry)
            "metric.telemetry.enabled=true",
        ]
    )
    fabric = Fabric(devices=devices, accelerator="cpu", strategy="dp")
    fabric._setup()
    total_envs = 16 * devices
    env = make_jax_env(cfg, total_envs)
    spec = env.spec
    obs_space = gym.spaces.Dict({"state": spec.to_gym_obs_space()})
    actor, critic, params = build_agent(
        fabric, cfg, obs_space, spec.action.to_gym_space(), jax.random.PRNGKey(0), None
    )
    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    fused, _, _ = make_sac_anakin_program(
        actor, critic, env, cfg, fabric, txs, total_envs, params, opt_state
    )
    params = fabric.replicate_pytree(params)
    opt_state = fabric.replicate_pytree(opt_state)
    env_state, obs = jax.jit(env.reset)(jax.random.PRNGKey(1))
    env_state = fabric.shard_pytree(env_state)
    obs = fabric.shard_pytree(obs)
    obs_dim = int(np.prod(spec.obs_shape))
    act_dim = int(np.prod(spec.action.shape))
    ring = ring_init(
        ring_capacity(int(cfg.buffer.size), total_envs),
        total_envs,
        ring_row_specs(obs_dim, act_dim),
        sharding=fabric.sharding(None, "data"),
    )
    stats = {
        "ep_return_sum": jnp.float32(0),
        "ep_length_sum": jnp.float32(0),
        "ep_count": jnp.float32(0),
        "losses": jnp.zeros((3,), jnp.float32),
    }
    args = (params, opt_state, env_state, obs, ring, jax.random.PRNGKey(2), stats, jnp.asarray(1))
    return fused, args


@register_fused_program(
    "replay.ring_write",
    min_donated=1,
    doc="device replay ring wraparound append (donated carry, standalone backend path)",
)
def _aot_ring_write_program():
    """The standalone ring write ``DeviceRingSampler.add`` dispatches (the
    fused topology inlines the same function): the ring carry must stay donated
    and the program host-transfer-free."""
    from sheeprl_tpu.data.device_ring import ring_init

    ring = ring_init(16, 4, ring_row_specs(3, 1))
    rows = {
        k: np.zeros((2, 4, *shape), dtype) for k, (shape, dtype) in ring_row_specs(3, 1).items()
    }
    return jax.jit(ring_write, donate_argnums=(0,)), (ring, rows)


@register_fused_program(
    "replay.ring_sample",
    donated=False,
    doc="device replay ring uniform Feistel sample (pure read, standalone backend path)",
)
def _aot_ring_sample_program():
    from sheeprl_tpu.data.device_ring import ring_init

    ring = ring_init(16, 4, ring_row_specs(3, 1))
    fn = jax.jit(ring_sample, static_argnames=("batch_size", "n_samples"))
    return fn, (ring, jax.random.PRNGKey(0), 8, 2)


def run_sac_anakin(fabric, cfg: Dict[str, Any]):
    """The fused off-policy training loop (registered as ``sac_anakin``)."""
    backend = str(cfg.env.get("backend", "host") or "host").lower()
    if backend != "jax":
        raise ValueError(
            f"{cfg.algo.name} requires the on-device env plane: set env.backend=jax "
            f"(got {backend!r}); host envs cannot live inside the fused program"
        )
    buffer_backend = str(cfg.buffer.get("backend", "local") or "local").lower()
    if buffer_backend != "device":
        raise ValueError(
            f"{cfg.algo.name} requires the device-resident replay ring: set "
            f"buffer.backend=device (got {buffer_backend!r}); a host replay buffer "
            "cannot live inside the fused program"
        )
    if len(cfg.algo.cnn_keys.encoder) > 0:
        raise ValueError("the anakin topology supports mlp observations only (cnn_keys must be empty)")
    if len(cfg.algo.mlp_keys.encoder) != 1:
        raise ValueError(
            f"the anakin topology expects exactly one mlp key, got {cfg.algo.mlp_keys.encoder!r}"
        )
    if int(cfg.algo.learning_starts) > 0:
        warnings.warn(
            f"{cfg.algo.name} ignores algo.learning_starts={cfg.algo.learning_starts}: the first "
            "fused iteration writes its whole rollout into the ring before sampling"
        )

    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")

    total_num_envs = int(cfg.env.num_envs * world_size)
    # scale the compile warmup to fused-iteration granularity (see run_anakin)
    tcfg = cfg.metric.get("telemetry") or {}
    if tcfg and int(tcfg.get("compile_warmup_steps") or 0) > 0:
        cfg.metric.telemetry.compile_warmup_steps = max(
            int(tcfg.get("compile_warmup_steps")),
            8 * total_num_envs * int(cfg.algo.rollout_steps),
        )
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)
    if world_size > 1 and total_num_envs % world_size != 0:
        raise ValueError(f"num_envs*world_size ({total_num_envs}) must divide the mesh ({world_size})")
    env = make_jax_env(cfg, total_num_envs)
    spec = env.spec
    if spec.action.kind != "continuous":
        raise ValueError(
            f"Only continuous action space is supported for the SAC agent (env {cfg.env.id!r} is "
            f"{spec.action.kind})"
        )
    mlp_key = cfg.algo.mlp_keys.encoder[0]
    observation_space = gym.spaces.Dict({mlp_key: spec.to_gym_obs_space()})
    action_space = spec.action.to_gym_space()
    obs_dim = int(np.prod(spec.obs_shape))
    act_dim = int(np.prod(spec.action.shape))

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key, env_key = jax.random.split(key, 3)
    actor, critic, params = build_agent(
        fabric, cfg, observation_space, action_space, agent_key, state["agent"] if state else None
    )

    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    if state is not None:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    # the durability twin: capacity-row host buffer the ring snapshots into at
    # checkpoint cadence. memmap is forced off — the snapshot REPLACES the
    # backing arrays wholesale (ring_to_buffer), which a memmap cannot survive,
    # and the hot path never touches host memory anyway.
    capacity = ring_capacity(int(cfg.buffer.size) if not cfg.dry_run else total_num_envs, total_num_envs)
    rb = ReplayBuffer(capacity, total_num_envs, memmap=False, obs_keys=("observations",))
    if state is not None and "rb" in state:
        rb = state["rb"]

    ring_sharding = fabric.sharding(None, "data") if world_size > 1 else None
    sampler = make_replay_sampler(
        rb,
        cfg.buffer.get("prefetch"),
        backend="device",
        sample_kwargs=dict(
            batch_size=cfg.algo.per_rank_batch_size * world_size,
            sample_next_obs=bool(cfg.buffer.sample_next_obs),
        ),
        sharding=ring_sharding,
        seed=int(cfg.seed),
        name="sac-device-ring",
    )
    telemetry.attach_sampler(sampler)
    if sampler.ring is None:
        sampler.ring = ring_init(
            capacity, total_num_envs, ring_row_specs(obs_dim, act_dim), sharding=ring_sharding
        )

    policy_steps_per_iter = int(total_num_envs * cfg.algo.rollout_steps)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * policy_steps_per_iter // world_size if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    anakin_step, rollout_only, grad_steps_per_iter = make_sac_anakin_program(
        actor, critic, env, cfg, fabric, txs, total_num_envs, params, opt_state
    )

    if world_size > 1:
        params = fabric.replicate_pytree(params)
        opt_state = fabric.replicate_pytree(opt_state)

    env_state, obs = jax.jit(env.reset)(env_key)
    if world_size > 1:
        env_state = fabric.shard_pytree(env_state)
        obs = fabric.shard_pytree(obs)

    ring = sampler.ring

    stats = {
        "ep_return_sum": jnp.float32(0.0),
        "ep_length_sum": jnp.float32(0.0),
        "ep_count": jnp.float32(0.0),
        "losses": jnp.zeros((3,), jnp.float32),
    }
    _zero = jnp.float32(0.0)
    last_ep_stats = {"ep_return_sum": 0.0, "ep_length_sum": 0.0, "ep_count": 0.0}

    bench = BenchWindow()

    rollout_seconds = None
    if not timer.disabled:
        rollout_seconds = _measure_rollout_seconds(rollout_only, (params, env_state, obs, key))

    for iter_num in range(start_iter, total_iters + 1):
        bench.maybe_start(policy_step, sync_tree=stats["losses"])
        policy_step += policy_steps_per_iter

        t0 = time.perf_counter()
        # one-shot injected learning pathology (resilience.fault=lr_spike):
        # identity unless the fault armed this iteration
        params = apply_armed_learn_fault(params)
        params, opt_state, env_state, obs, ring, key, stats, learn = anakin_step(
            params, opt_state, env_state, obs, ring, key, stats, jnp.asarray(iter_num)
        )
        # keep the live ring reachable for the checkpoint snapshot path, and
        # account the fused program's in-program writes (this topology bypasses
        # sampler.add, so the Buffer/ring_* overwrite gauge is fed here)
        sampler.ring = ring
        sampler.note_writes(int(cfg.algo.rollout_steps))
        # one scalar sync per ITERATION (T * num_envs env steps): keeps the host
        # from racing the device queue and makes the wall-time split honest
        jax.block_until_ready(stats["losses"])
        elapsed = time.perf_counter() - t0

        split_frac = (
            min(rollout_seconds / elapsed, 1.0)
            if (rollout_seconds and elapsed > 0)
            else 1.0
        )
        timer("Time/rollout_time").add(elapsed * split_frac)
        timer("Time/train_time").add(elapsed * (1.0 - split_frac))

        telemetry.observe_train(grad_steps_per_iter, stats["losses"])
        telemetry.observe_learn(learn)
        if telemetry.enabled:
            ep_count = float(stats["ep_count"]) - last_ep_stats["ep_count"]
            if ep_count >= 1.0:
                mean_ret = (float(stats["ep_return_sum"]) - last_ep_stats["ep_return_sum"]) / ep_count
                mean_len = (float(stats["ep_length_sum"]) - last_ep_stats["ep_length_sum"]) / ep_count
                telemetry.observe_episodes([mean_ret], [mean_len], count=int(ep_count))
                last_ep_stats = {
                    k: float(stats[k]) for k in _STATS_ACC
                }
        if telemetry.wants_program("sac_anakin_step"):
            telemetry.register_program(
                "sac_anakin_step",
                anakin_step,
                (params, opt_state, env_state, obs, ring, key, stats, jnp.asarray(iter_num)),
                units=grad_steps_per_iter,
            )
        telemetry.step(policy_step)
        resilience.step(policy_step)

        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                # the ONLY steady-state device->host traffic: a handful of scalars
                stats_np = {k: np.asarray(stats[k]) for k in _STATS_ACC}
                losses_np = np.asarray(stats["losses"])
                if aggregator and not aggregator.disabled:
                    if stats_np["ep_count"] > 0:
                        aggregator.update(
                            "Rewards/rew_avg", float(stats_np["ep_return_sum"] / stats_np["ep_count"])
                        )
                        aggregator.update(
                            "Game/ep_len_avg", float(stats_np["ep_length_sum"] / stats_np["ep_count"])
                        )
                    aggregator.update("Loss/value_loss", float(losses_np[0]))
                    aggregator.update("Loss/policy_loss", float(losses_np[1]))
                    aggregator.update("Loss/alpha_loss", float(losses_np[2]))
                stats = dict(stats, ep_return_sum=_zero, ep_length_sum=_zero, ep_count=_zero)
                last_ep_stats = {"ep_return_sum": 0.0, "ep_length_sum": 0.0, "ep_count": 0.0}
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    fused_seconds = timers.get("Time/rollout_time", 0.0) + timers.get(
                        "Time/train_time", 0.0
                    )
                    if fused_seconds > 0:
                        logger.log_metrics(
                            {"Time/sps_env_interaction": (policy_step - last_log) / fused_seconds},
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step

        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            # snapshot to host numpy first: params/opt_state/ring are donated
            # into the NEXT anakin_step call, and an async checkpoint backend
            # must never hold references into donated device buffers
            ckpt_state = {
                "agent": packed_device_get(params),
                "opt_state": packed_device_get(opt_state),
                "iter_num": iter_num * world_size,
                "batch_size": int(cfg.algo.per_rank_batch_size * world_size),
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            with timer("Time/checkpoint_time"):
                if cfg.buffer.checkpoint:
                    # ring -> host buffer (cursor + fill included): the snapshot
                    # then rides the exact _ckpt_rb durability protocol
                    sampler.sync_to_host()
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    bench.finish(policy_step, sync_tree=stats["losses"])
    sampler.close()
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(actor.apply, params["actor"], fabric, cfg, log_dir)
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
