"""SAC agent, Flax-native.

Capability parity with the reference agent (sheeprl/algos/sac/agent.py:20-371):
tanh-squashed Gaussian actor with action rescaling, twin (or n-way) Q critics,
automatic entropy tuning via a learned log-alpha, EMA target critics.

TPU-native structure: the critic ensemble is a single vmapped module with stacked
params — one apply evaluates all n critics as batched matmuls on the MXU (the
reference loops over n separate modules, agent.py:219-230). The agent/player split
collapses into pure functions over one params pytree.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import MLP

LOG_STD_MAX = 2.0
LOG_STD_MIN = -5.0


class SACActor(nn.Module):
    """MLP -> (mean, log_std) heads; actions are tanh-squashed and rescaled to the
    env bounds (reference agent.py:57-145, Eq. 26 of arXiv:1812.05905)."""

    action_dim: int
    hidden_size: int = 256
    action_low: Tuple[float, ...] = (-1.0,)
    action_high: Tuple[float, ...] = (1.0,)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu", dtype=self.dtype)(obs)
        mean = nn.Dense(self.action_dim, dtype=self.dtype)(x)
        log_std = nn.Dense(self.action_dim, dtype=self.dtype)(x)
        std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
        return mean, std

    @property
    def action_scale(self) -> np.ndarray:
        return (np.asarray(self.action_high) - np.asarray(self.action_low)) / 2.0

    @property
    def action_bias(self) -> np.ndarray:
        return (np.asarray(self.action_high) + np.asarray(self.action_low)) / 2.0


def squash_and_logprob(
    mean: jax.Array, std: jax.Array, key: jax.Array, action_scale, action_bias
) -> Tuple[jax.Array, jax.Array]:
    """Reparameterized sample -> tanh squash -> rescale; log-prob with the tanh
    change-of-variable correction (reference agent.py:110-145)."""
    eps = jax.random.normal(key, mean.shape, dtype=mean.dtype)
    x_t = mean + std * eps
    y_t = jnp.tanh(x_t)
    action = y_t * action_scale + action_bias
    log_prob = -0.5 * (((x_t - mean) / std) ** 2 + 2 * jnp.log(std) + jnp.log(2 * jnp.pi))
    log_prob = log_prob - jnp.log(action_scale * (1 - y_t**2) + 1e-6)
    return action, log_prob.sum(-1, keepdims=True)


def greedy_action(mean: jax.Array, action_scale, action_bias) -> jax.Array:
    return jnp.tanh(mean) * action_scale + action_bias


class SACCritic(nn.Module):
    """Q(s, a) MLP (reference agent.py:20-54)."""

    hidden_size: int = 256
    num_critics: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        return MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=self.num_critics,
            activation="relu",
            dtype=self.dtype,
        )(x)


class CriticEnsemble(nn.Module):
    """n independent critics with stacked params evaluated in one vmapped apply →
    output [*batch, n] (replaces the reference's python loop over critic modules)."""

    n: int
    hidden_size: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        ensemble = nn.vmap(
            SACCritic,
            in_axes=None,
            out_axes=-1,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            axis_size=self.n,
        )
        out = ensemble(hidden_size=self.hidden_size, num_critics=1, dtype=self.dtype)(obs, action)
        return out.reshape(*out.shape[:-2], self.n)


def build_agent(
    fabric,
    cfg,
    observation_space,
    action_space,
    key: jax.Array,
    state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACActor, CriticEnsemble, Dict[str, Any]]:
    """Create modules + the params pytree {actor, critic, target_critic, log_alpha}
    (role of reference build_agent, sheeprl/algos/sac/agent.py:318-371)."""
    obs_dim = sum(prod(observation_space[k].shape) for k in cfg.algo.mlp_keys.encoder)
    act_dim = int(prod(action_space.shape))
    actor = SACActor(
        action_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        action_low=tuple(np.asarray(action_space.low, dtype=np.float32).reshape(-1).tolist()),
        action_high=tuple(np.asarray(action_space.high, dtype=np.float32).reshape(-1).tolist()),
        dtype=fabric.compute_dtype,
    )
    critic = CriticEnsemble(n=cfg.algo.critic.n, hidden_size=cfg.algo.critic.hidden_size, dtype=fabric.compute_dtype)
    k_actor, k_critic = jax.random.split(key)
    dummy_obs = jnp.zeros((1, obs_dim), dtype=jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), dtype=jnp.float32)
    actor_params = actor.init(k_actor, dummy_obs)["params"]
    critic_params = critic.init(k_critic, dummy_obs, dummy_act)["params"]
    params = {
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
        "log_alpha": jnp.log(jnp.asarray([cfg.algo.alpha.alpha], dtype=jnp.float32)),
    }
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state)
    return actor, critic, params
