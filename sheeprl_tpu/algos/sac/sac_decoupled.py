"""SAC, decoupled (actor–learner MPMD) training — capability parity with
sheeprl/algos/sac/sac_decoupled.py:33-588.

Same TPU-native topology as the decoupled PPO module: the player owns the envs and
the replay buffer on the host (CPU backend act path, reference player():33-353); the
learner owns the accelerator mesh in its own thread and runs the fused G-step SAC
program (reference trainer():356-545). The data plane ships sampled replay blocks
(the reference's pickled scatter, sac_decoupled.py:243-257); the weight plane
returns the actor params, blocking the player like the reference's flattened-actor
broadcast (sac_decoupled.py:266-272)."""

from __future__ import annotations

import os
from functools import partial
import queue
import threading
import warnings
from typing import Any, Dict, Optional

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import build_agent, squash_and_logprob
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.parallel.distributed import (
    BroadcastChannel,
    ChannelError,
    publish_channel_error,
    replicated_to_host,
)
from sheeprl_tpu.obs import NullTelemetry, build_role_telemetry, build_telemetry
from sheeprl_tpu.resilience import (
    NullResilience,
    apply_armed_learn_fault,
    build_resilience,
    channel_options,
)
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, Ratio, save_configs


def _build_sac_train(cfg, actor, critic, target_entropy, policy_steps_per_iter):
    """The fused multi-gradient-step SAC train program + its optimizer state
    builder — ONE construction shared by the channel trainer (``_trainer_loop``),
    the experience-service learner (``_service_learner``), the coupled loop AND
    the AOT contract registry: everything delegates to ``sac.make_train_phase``,
    so every backend runs (and ``lint --aot`` lowers) the bit-identical donated
    program. ``policy_steps_per_iter`` is the GLOBAL env transitions per driver
    iteration (it sets the target-EMA period in iterations, exactly as before)."""
    from sheeprl_tpu.algos.sac.sac import build_optimizers, init_opt_state, make_train_phase

    txs = build_optimizers(cfg)
    train_phase = make_train_phase(cfg, actor, critic, target_entropy, policy_steps_per_iter, txs=txs)
    return train_phase, partial(init_opt_state, txs)


def _trainer_loop(
    fabric, cfg, actor, critic, params, target_entropy, data_q, params_q, error, geometry=None,
    resume_state=None, telemetry=None, resilience=None,
):
    # ``telemetry``: the learner role's own stream (two-process topology only —
    # the threaded trainer shares the player's process, whose telemetry already
    # observes it; a second writer would also race the shared timer registry).
    # ``resilience``: likewise the learner PROCESS's peer facade (heartbeats,
    # rank-targeted faults, preempt-request publication, dead-peer aborts) —
    # the threaded trainer leaves all of that to the player's monitor.
    from contextlib import nullcontext

    telemetry = telemetry if telemetry is not None else NullTelemetry()
    resilience = resilience if resilience is not None else NullResilience()
    train_span = timer("Time/train_time") if telemetry.enabled else nullcontext()
    try:
        # two-process topology: batch/EMA-period math follows the PLAYER's device
        # count (the roles may own different meshes)
        world_size = fabric.world_size if geometry is None else int(geometry["player_world_size"])
        if resume_state is not None:
            # reference trainer resume (sac_decoupled.py:406-434): restore the
            # slice's params from the checkpoint, not the seed-matched init
            params = jax.tree_util.tree_map(jnp.asarray, resume_state["agent"])
        policy_steps_per_iter = int(cfg.env.num_envs * world_size)
        train_phase, init_opt_state = _build_sac_train(
            cfg, actor, critic, target_entropy, policy_steps_per_iter
        )
        opt_state = init_opt_state(params)
        if resume_state is not None and resume_state.get("opt_state") is not None:
            opt_state = jax.tree_util.tree_map(jnp.asarray, resume_state["opt_state"])

        mesh_size = fabric.world_size
        if mesh_size > 1:
            params = fabric.replicate_pytree(params)
            opt_state = fabric.replicate_pytree(opt_state)

        key = jax.random.PRNGKey(cfg.seed + 1)
        last_step = 0
        while True:
            msg = data_q.get()
            if msg is None:
                telemetry.close(last_step)
                params_q.put(None)
                return
            data, iter_num, want_opt_state = msg
            units = int(data["rewards"].shape[0])
            with train_span:
                if mesh_size > 1:
                    # every learner process holds the full broadcast block; sharding the
                    # batch axis over the slice mesh forms the global array (the G-scan
                    # leading axis stays unsharded)
                    data = jax.device_put(data, fabric.sharding(None, "data"))
                key, train_key = jax.random.split(key)
                # one-shot injected learning pathology (resilience.fault=lr_spike
                # targeting the learner process): identity unless armed
                params = apply_armed_learn_fault(params)
                params, opt_state, mean_losses, learn = train_phase(
                    params, opt_state, data, jnp.asarray(iter_num), np.asarray(train_key)
                )
                # opt_state only crosses when the player is about to checkpoint
                # (reference parity with the PPO weight plane's want_opt_state).
                # replicated_to_host handles the multi-process slice mesh, where
                # np.asarray refuses non-addressable (but replicated) outputs.
                # The Learn/* block rides host-side so the PLAYER's stream (the
                # run's primary) carries the learning window too — it is a
                # handful of scalars next to the losses the reply already syncs.
                reply = (
                    replicated_to_host(params),
                    replicated_to_host(opt_state) if want_opt_state else None,
                    replicated_to_host(mean_losses),
                    replicated_to_host(learn),
                )
            params_q.put(reply)
            last_step = int(iter_num) * policy_steps_per_iter
            telemetry.observe_train(units, reply[2])
            telemetry.observe_learn(reply[3])
            telemetry.step(last_step)
            # publishes this rank's preempt request / heartbeat step and raises
            # RankFailureError on a declared-dead peer (never hang on one)
            resilience.step(last_step)
    except BaseException as e:
        error["exc"] = e
        # out-of-band marker FIRST: on a non-src learner rank the channel put
        # below is a sequence-counter no-op (BroadcastChannel writes only on
        # src), so the marker is the only signal the blocked peers ever get
        publish_channel_error(f"learner train loop failed: {e!r:.300}")
        # If the crash came from a channel collective the broadcast plane is
        # desynced — another lockstep put can block forever and bury the real
        # traceback. Only unblock the player while the channel is healthy.
        if not isinstance(e, ChannelError):
            try:
                params_q.put(None)
            except ChannelError:
                pass


def _learner_process(fabric, cfg: Dict[str, Any]):
    """Learner role of the multi-process topology (reference trainer ranks,
    sac_decoupled.py:356-545): one process of the learner SLICE, whose DP mesh
    spans every learner process's devices; replay blocks in, updated params out,
    over the host channels (all slice members run this same program)."""
    from sheeprl_tpu.parallel import distributed

    env = make_env(cfg, cfg.seed, 0, None, "learner")()
    observation_space = env.observation_space
    action_space = env.action_space
    env.close()
    # same seed as the player's rank-0 init -> identical initial params
    key = fabric.seed_everything(cfg.seed)
    key, agent_key = jax.random.split(key)
    actor, critic, params = build_agent(fabric, cfg, observation_space, action_space, agent_key, None)
    target_entropy = -float(int(np.prod(action_space.shape)))
    # the learner's peer facade comes up BEFORE the first blocking channel op:
    # its heartbeat lets the player distinguish "learner is compiling" from
    # "learner is dead", and its abort check breaks our own waits
    telemetry = build_role_telemetry(
        fabric, cfg, "learner",
        rank=distributed.process_index(),
        leader=distributed.process_index() == 1,
    )
    resilience = build_resilience(fabric, cfg, None, telemetry=telemetry)
    opts = channel_options(cfg)
    data_q, params_q = BroadcastChannel(src=0, **opts), BroadcastChannel(src=1, **opts)
    geometry = data_q.get()
    if geometry is None:  # player failed before the first block
        params_q.put(None)  # pairs the player's cleanup ack-consume
        resilience.finalize()
        return
    resume_state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        try:
            resume_state = load_checkpoint(cfg.checkpoint.resume_from)
        except Exception as exc:
            # surface a load failure on the weight plane like any learner crash
            # (the player otherwise blocks on params_q.get until the channel
            # timeout). The put is a real write only on the params src rank;
            # the KV marker covers every other learner rank.
            publish_channel_error(f"checkpoint resume load failed: {exc!r:.300}")
            try:
                params_q.put(None)
            except ChannelError:
                pass
            raise
        # the slice only needs params + opt_state; drop the (potentially
        # GB-sized) replay buffer the player-side state carries
        resume_state.pop("rb", None)
    error: Dict[str, Any] = {}
    try:
        _trainer_loop(
            fabric, cfg, actor, critic, params, target_entropy, data_q, params_q, error,
            geometry=geometry, resume_state=resume_state, telemetry=telemetry,
            resilience=resilience,
        )
        if "exc" in error:
            # pair the player's final sentinel — unless the crash WAS the channel,
            # whose collectives are desynced and would hang instead of pairing
            if not isinstance(error["exc"], ChannelError):
                try:
                    data_q.get()
                    params_q.put(None)
                except ChannelError:
                    pass
            raise error["exc"]
    finally:
        resilience.finalize()


# ---------------------------------------------------------------------------------
# buffer.backend=service: multi-actor ingestion into a standalone experience plane
# (sheeprl_tpu/data/service.py, howto/fleet.md). Ranks 0..A-1 run env/act loops
# that ship rows append-only over the KV object plane; the last rank hosts the
# replay buffer + the SAME fused donated train program and samples exactly like
# the local backend (sharded staging, prefetch, donation unchanged). Acting and
# learning are decoupled: K actors' ingestion scales with K while the learner
# trains at its own pace and publishes weights on a version-keyed plane.
# ---------------------------------------------------------------------------------


def _service_actor(fabric, cfg: Dict[str, Any], layout: Dict[str, Any]):
    """One actor process of the service topology: env stepping + acting +
    append-only row ingestion + non-blocking weight refresh. Never trains, never
    samples, never blocks on the learner (except the bounded flow-control
    watermark and the exit gate)."""
    from sheeprl_tpu.data.service import (
        ActorDataflow,
        ExperienceWriter,
        ServiceError,
        WeightSubscriber,
        coordination_kv,
        service_namespace,
        service_options,
    )
    from sheeprl_tpu.parallel import distributed

    rank = distributed.process_index()
    actors = int(layout["actors"])
    num_envs = int(cfg.env.num_envs)  # per actor; actor PROCESSES are the scale axis
    policy_steps_per_iter = num_envs * actors  # global transitions per iteration

    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        # counters only: params come from the weight plane, the buffer lives
        # with the learner — don't hold a (potentially buffer-sized) state
        state = load_checkpoint(cfg.checkpoint.resume_from)
        state.pop("rb", None)

    log_dir = None
    logger = None
    if rank == 0:
        log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, share=False)
        logger = get_logger(fabric, cfg, log_dir=log_dir)
        fabric.logger = logger
        if logger is not None:
            logger.log_hyperparams(cfg.as_dict())
        fabric.print(f"Log dir: {log_dir}")
        telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    else:
        telemetry = build_role_telemetry(fabric, cfg, f"actor{rank}", rank=rank)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)
    preempted = False
    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * num_envs + i,
                rank * num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    mlp_keys = cfg.algo.mlp_keys.encoder

    # agent init from the SHARED seed (identical across every rank, learner
    # included — the service learner never ships initial weights); the act
    # key chain then forks per actor so exploration differs
    key = fabric.seed_everything(cfg.seed)
    key, agent_key = jax.random.split(key)
    actor, _critic, params = build_agent(
        fabric, cfg, observation_space, action_space, agent_key, None
    )
    key = jax.random.fold_in(key, rank)
    action_scale = jnp.asarray(actor.action_scale, dtype=jnp.float32)
    action_bias = jnp.asarray(actor.action_bias, dtype=jnp.float32)
    if rank == 0 and fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if rank == 0 and not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    kv = coordination_kv()
    if kv is None:
        raise ServiceError(
            "buffer.backend=service needs the jax.distributed coordination "
            "service (launch through the gang supervisor or bring up "
            "jax.distributed before the run)"
        )
    ns = service_namespace()
    opts = service_options(cfg)
    writer = ExperienceWriter(
        kv,
        ns,
        rank,
        max_inflight=opts["max_inflight"],
        flush_every=opts["flush_every"],
        poll_s=opts["poll_s"],
        timeout_s=opts["timeout_s"],
        abort_check=opts["abort_check"],
    )
    subscriber = WeightSubscriber(
        kv,
        ns,
        poll_s=opts["poll_s"],
        timeout_s=opts["timeout_s"],
        abort_check=opts["abort_check"],
    )
    # dataflow lineage: every telemetry window carries this actor's weight
    # version/lag + ingestion counters (howto/observability.md)
    telemetry.attach_dataflow(ActorDataflow(writer, subscriber))
    poll_weights = opts["poll_weights"]

    act = ActPlacement(fabric, lambda p: p["actor"])
    act_on_cpu = act.on_cpu

    @partial(jax.jit, backend="cpu" if act_on_cpu else None)
    def act_fn(actor_params, obs: jax.Array, key):
        key, step_key = jax.random.split(key)
        mean, std = actor.apply({"params": actor_params}, obs)
        actions, _ = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
        return actions, key

    act_params = act.view(params)
    key = act.place(key)
    weight_version = 0

    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    start_iter = int(state["iter_num"]) + 1 if state is not None else 1
    if state is not None:
        # re-prefill window (reference sac.py:222-226): a resumed run refills
        # from the env before the learner trains on a near-empty buffer
        learning_starts += start_iter
    policy_step = (start_iter - 1) * policy_steps_per_iter
    last_log = 0

    step_data: Dict[str, np.ndarray] = {}
    # disjoint reset-seed spans per actor (SyncVectorEnv seeds env i with
    # seed + i, so a +rank offset would overlap neighbouring actors)
    obs = envs.reset(seed=cfg.seed + rank * num_envs)[0]

    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                flat_obs = prepare_obs(fabric, obs, mlp_keys=mlp_keys, num_envs=num_envs)
                actions, key = act_fn(act_params, flat_obs, key)
                actions = np.asarray(actions)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                np.asarray(actions).reshape(envs.action_space.shape)
            )
            rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, -1)

        ep_info = infos.get("final_info", infos)
        if "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
        final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
        if final_obs_arr is not None:
            for idx in range(num_envs):
                if final_obs_arr[idx] is not None:
                    for k in mlp_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])
        flat_real_next = np.concatenate(
            [real_next_obs[k].reshape(num_envs, -1) for k in mlp_keys], axis=-1
        ).astype(np.float32)

        step_data["terminated"] = np.asarray(terminated).reshape(1, num_envs, -1).astype(np.float32)
        step_data["truncated"] = np.asarray(truncated).reshape(1, num_envs, -1).astype(np.float32)
        step_data["actions"] = np.asarray(actions).reshape(1, num_envs, -1).astype(np.float32)
        step_data["observations"] = np.concatenate(
            [np.asarray(obs[k]).reshape(num_envs, -1) for k in mlp_keys], axis=-1
        ).astype(np.float32)[np.newaxis]
        if not cfg.buffer.sample_next_obs:
            step_data["next_observations"] = flat_real_next[np.newaxis]
        step_data["rewards"] = rewards[np.newaxis]
        # append-only ingestion (no local buffer): rows land in the learner's
        # env slots [rank*num_envs, (rank+1)*num_envs)
        writer.add(step_data, steps=policy_step)
        obs = next_obs

        # non-blocking weight refresh — the act path never waits on a round
        # (poll_weights=false is the deliberate stale-actor injection the
        # weight_staleness detector smoke rides)
        payload = subscriber.poll() if poll_weights else None
        if payload is not None:
            act_params = act.place(payload["tree"])
            weight_version = int(payload["version"])
            writer.weight_version = weight_version  # rows now carry this lineage

        preempted = resilience.preempt_requested()
        telemetry.step(policy_step)
        resilience.step(policy_step)
        if (
            rank == 0
            and cfg.metric.log_level > 0
            and (policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters)
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    logger.log_metrics({"Params/weight_version": weight_version}, policy_step)
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step
        if preempted:
            break

    writer.close(preempted=preempted)
    telemetry.emit_event("service", step=policy_step, role="actor", **writer.telemetry_snapshot())
    # exit gate: leave together with the learner, so the gang's teardown
    # grace window never SIGTERMs a learner still draining the backlog
    if not writer.wait_done(timeout_s=float((cfg.buffer.get("service") or {}).get("done_timeout") or 300.0)):
        warnings.warn("experience service: the learner never published its done marker")
    payload = subscriber.poll() if poll_weights else None
    if payload is not None:
        act_params = act.place(payload["tree"])

    envs.close()
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and rank == 0 and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(actor.apply, jax.tree_util.tree_map(jnp.asarray, act_params), fabric, cfg, log_dir)
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()


def _service_learner(fabric, cfg: Dict[str, Any], layout: Dict[str, Any]):
    """The service learner: hosts the experience buffer (fed by the ingest
    thread), samples through the UNCHANGED replay sampler (sharded staging +
    prefetch), runs the same donated fused train program as the local backend,
    and publishes weights on the version-keyed plane. Owns checkpoints."""
    import time as _time

    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer
    from sheeprl_tpu.data.service import (
        ExperienceService,
        LearnerDataflow,
        ServiceError,
        WeightPublisher,
        coordination_kv,
        service_namespace,
        service_options,
    )
    from sheeprl_tpu.parallel import distributed
    from sheeprl_tpu.utils.logger import run_base_dir

    rank = distributed.process_index()
    actors = int(layout["actors"])
    num_envs = int(cfg.env.num_envs)
    total_envs = actors * num_envs
    policy_steps_per_iter = total_envs

    env = make_env(cfg, cfg.seed, 0, None, "learner")()
    observation_space = env.observation_space
    action_space = env.action_space
    env.close()
    key = fabric.seed_everything(cfg.seed)  # same init as every actor
    key, agent_key = jax.random.split(key)
    actor, critic, params = build_agent(fabric, cfg, observation_space, action_space, agent_key, None)
    target_entropy = -float(int(np.prod(action_space.shape)))

    telemetry = build_role_telemetry(fabric, cfg, "learner", rank=rank, leader=True)
    resilience = build_resilience(fabric, cfg, None, telemetry=telemetry)
    try:
        kv = coordination_kv()
        if kv is None:
            raise ServiceError(
                "buffer.backend=service needs the jax.distributed coordination service"
            )
        ns = service_namespace()
        opts = service_options(cfg)

        state = None
        if cfg.checkpoint.resume_from:
            from sheeprl_tpu.utils.checkpoint import load_checkpoint

            state = load_checkpoint(cfg.checkpoint.resume_from)
        if state is not None:
            params = jax.tree_util.tree_map(jnp.asarray, state["agent"])

        train_phase, init_opt_state = _build_sac_train(
            cfg, actor, critic, target_entropy, policy_steps_per_iter
        )
        opt_state = init_opt_state(params)
        if state is not None and state.get("opt_state") is not None:
            opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
        if fabric.world_size > 1:
            params = fabric.replicate_pytree(params)
            opt_state = fabric.replicate_pytree(opt_state)

        # the learner's artifact home: <run base>/learner — config.yaml next to
        # checkpoint/ so resume/eval resolve it with the standard rules
        learner_dir = str(run_base_dir(cfg.root_dir, cfg.run_name) / "learner")
        os.makedirs(learner_dir, exist_ok=True)
        save_configs(cfg, learner_dir)

        buffer_size = cfg.buffer.size // total_envs if not cfg.dry_run else 8
        rb = EnvIndependentReplayBuffer(
            max(buffer_size, 1),
            n_envs=total_envs,
            obs_keys=("observations",),
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(learner_dir, "memmap_buffer", f"rank_{rank}"),
        )
        rows_base = 0
        if state is not None and "rb" in state:
            rb = state["rb"]
        if state is not None:
            rows_base = int(state.get("service_rows") or 0)

        sampler = make_replay_sampler(
            rb,
            cfg.buffer.get("prefetch"),
            sample_kwargs=dict(
                batch_size=cfg.algo.per_rank_batch_size * fabric.world_size,
                sample_next_obs=bool(cfg.buffer.sample_next_obs),
            ),
            uint8_keys=(),
            sharding=fabric.sharding(None, "data") if fabric.num_devices > 1 else None,
            name="sac-service-prefetch",
        )
        telemetry.attach_sampler(sampler)

        service = ExperienceService(
            rb,
            kv,
            ns,
            layout["actor_ranks"],
            lock=sampler.lock,
            poll_s=opts["poll_s"],
            env_ids_of=lambda r: list(range(r * num_envs, (r + 1) * num_envs)),
            validate_args=bool(cfg.buffer.validate_args),
        ).start()
        publisher = WeightPublisher(kv, ns)
        publish_every = max(int((cfg.buffer.get("service") or {}).get("publish_every") or 1), 1)
        # dataflow lineage: learner windows carry per-actor weight lag, the
        # sampled-row age distribution and ingest latency from the service
        telemetry.attach_dataflow(LearnerDataflow(service, publisher))
        # version 1 immediately: resumed/late actors act on restored weights
        # without waiting for the first train round
        publisher.publish(replicated_to_host(params)["actor"])

        ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
        if state is not None and "ratio" in state:
            ratio.load_state_dict(state["ratio"])
        learning_starts = int(cfg.algo.learning_starts) if not cfg.dry_run else 0
        if state is not None and "rb" not in state:
            # re-prefill: without a restored buffer, wait for fresh rows before
            # training (the actors' learning_starts shift mirrors this)
            learning_starts += rows_base
        # feed the governor steps-past-prefill (the local loops' semantics): the
        # first warm consult then grants ~ratio x per-iteration rows, not a
        # ratio x learning_starts burst
        prefill_rows = max(learning_starts - policy_steps_per_iter, 0)
        checkpoint_every = int(cfg.checkpoint.every)
        last_checkpoint = rows_base
        window_every = int(
            (cfg.metric.get("telemetry") or {}).get("every") or cfg.metric.log_every
        )
        last_service_event = rows_base
        cum_gsteps = 0
        rounds = 0
        key = jax.random.PRNGKey(cfg.seed + 1)
        preempted = False
        mean_losses = None
        # fixed-size train rounds: the scan-based fused program compiles per
        # DISTINCT G, and the async grant cadence would otherwise produce many
        # one-off G values (each a fresh XLA compile). Accumulate grants into a
        # debt and train in rounds of the local topology's per-iteration grant —
        # ONE compiled shape in steady state, directly comparable learner
        # gradient-steps/sec (the fleet_ingest bench's B-side)
        round_size = max(int(policy_steps_per_iter * float(cfg.algo.replay_ratio)), 1)
        grant_debt = 0

        def checkpoint(rows: int, *, is_preempt: bool) -> None:
            ckpt_state = {
                "agent": replicated_to_host(params),
                "opt_state": replicated_to_host(opt_state),
                "ratio": ratio.state_dict(),
                "iter_num": rows // policy_steps_per_iter,
                "batch_size": cfg.algo.per_rank_batch_size * fabric.world_size,
                "service_rows": rows,
                "last_log": 0,
                "last_checkpoint": rows,
            }
            ckpt_path = os.path.join(learner_dir, "checkpoint", f"ckpt_{rows}_{rank}.ckpt")
            # quiesce both the prefetch worker AND the ingest thread writes: the
            # pickled buffer must not be a torn mid-add/mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_player",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, rows, preempted=is_preempt)

        while True:
            service.raise_pending()
            rows = rows_base + service.rows_total
            preempted = resilience.preempt_requested()
            eos = service.eos_all()
            warm = rows >= learning_starts and rows > 0 and not any(rb.empty)
            grant_debt += ratio(max(rows - prefill_rows, 0)) if warm else 0
            # EOS flushes the residual debt as one final (odd-shaped) round
            grant = (
                round_size
                if grant_debt >= round_size
                else grant_debt
                if eos
                else 0
            )
            if grant > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(grant)
                    key, train_key = jax.random.split(key)
                    params = apply_armed_learn_fault(params)
                    params, opt_state, mean_losses, learn = train_phase(
                        params,
                        opt_state,
                        data,
                        jnp.asarray(rows // policy_steps_per_iter),
                        np.asarray(train_key),
                    )
                grant_debt -= grant
                cum_gsteps += grant
                rounds += 1
                telemetry.observe_train(grant, mean_losses)
                telemetry.observe_learn(learn)
                if rounds % publish_every == 0:
                    publisher.publish(replicated_to_host(params)["actor"])
            elif not eos:
                _time.sleep(opts["poll_s"])  # let ingestion land
            telemetry.step(rows)
            resilience.step(rows)
            if rows - last_service_event >= window_every:
                last_service_event = rows
                telemetry.emit_event(
                    "service",
                    step=rows,
                    role="learner",
                    gradient_steps=cum_gsteps,
                    weight_version=publisher.version,
                    **service.telemetry_snapshot(),
                )
            if checkpoint_every > 0 and rows - last_checkpoint >= checkpoint_every:
                last_checkpoint = rows
                checkpoint(rows, is_preempt=False)
            if preempted or (eos and grant == 0):
                break

        rows = rows_base + service.rows_total
        if preempted or cfg.checkpoint.save_last or cfg.dry_run:
            checkpoint(rows, is_preempt=preempted or service.eos_preempted())
        publisher.publish(replicated_to_host(params)["actor"], final=True)
        telemetry.emit_event(
            "service",
            step=rows,
            role="learner",
            gradient_steps=cum_gsteps,
            weight_version=publisher.version,
            **service.telemetry_snapshot(),
        )
        service.mark_done()
        sampler.close()
        service.stop()
        wait_for_checkpoint()
        telemetry.close(rows)
    finally:
        resilience.finalize()


def _service_main(fabric, cfg: Dict[str, Any]):
    from sheeprl_tpu.data.service import service_layout
    from sheeprl_tpu.parallel import distributed

    layout = service_layout(cfg)
    if layout["learners"] != 1:
        raise ValueError(
            f"buffer.backend=service currently takes exactly ONE learner process "
            f"(got {layout['learners']}: {layout['nprocs']} processes, "
            f"{layout['actors']} actors) — the learner's own local mesh is the "
            "train mesh; multi-process learner slices ride buffer.backend=local's "
            "channel topology"
        )
    rank = distributed.process_index()
    if rank >= layout["actors"]:
        fabric.process_group = layout["learner_ranks"]
    fabric.local_mesh = True
    fabric._setup()
    if rank >= layout["actors"]:
        return _service_learner(fabric, cfg, layout)
    return _service_actor(fabric, cfg, layout)


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    from sheeprl_tpu.parallel import distributed

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    if str(cfg.buffer.get("backend", "local")) == "service":
        # standalone experience plane: K actor processes + 1 learner process
        # (raises with an actionable message on a single-process launch)
        return _service_main(fabric, cfg)

    two_process = distributed.process_count() >= 2
    if two_process:
        # process 0: player on its own devices; processes 1..N-1: learner slice
        # sharing one DP mesh (reference trainer subgroup, sac_decoupled.py:548-588)
        if distributed.process_index() >= 1:
            fabric.process_group = tuple(range(1, distributed.process_count()))
        fabric.local_mesh = True
        fabric._setup()
        if distributed.process_index() >= 1:
            return _learner_process(fabric, cfg)

    # Resume (reference sac_decoupled.py:43-44,86-123): each role loads the
    # checkpoint from its own filesystem — the player (after the role split, so
    # learner processes don't pay a throwaway load of a potentially buffer-sized
    # state) restores counters, ratio, params and the replay buffer; the learner
    # slice restores params + opt state inside _learner_process.
    state = None
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        state = load_checkpoint(cfg.checkpoint.resume_from)

    # read AFTER the role split: the two-process branch rebuilds the mesh with only
    # this process's devices, and all player-local sizes must follow that mesh
    rank = fabric.global_rank
    world_size = fabric.world_size

    # any player-side failure must release a learner blocked in a channel; the
    # KV-backed channels are STATEFUL (sequence counters), so the crash path must
    # reuse the live instances once they exist
    _protocol_done = False
    data_q: Any = None
    params_q: Any = None
    try:
        log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, share=not two_process)
        logger = get_logger(fabric, cfg, log_dir=log_dir)
        fabric.logger = logger
        if logger is not None:
            logger.log_hyperparams(cfg.as_dict())
        fabric.print(f"Log dir: {log_dir}")
        telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
        resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

        total_num_envs = int(cfg.env.num_envs * world_size)
        vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
        envs = vectorized_env(
            [
                make_env(
                    cfg,
                    cfg.seed + rank * total_num_envs + i,
                    rank * total_num_envs,
                    log_dir if rank == 0 else None,
                    "train",
                    vector_env_idx=i,
                )
                for i in range(total_num_envs)
            ],
            autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
        )
        action_space = envs.single_action_space
        observation_space = envs.single_observation_space
        if not isinstance(action_space, gym.spaces.Box):
            raise ValueError("Only continuous action space is supported for the SAC agent")
        mlp_keys = cfg.algo.mlp_keys.encoder

        key = fabric.seed_everything(cfg.seed + rank)
        key, agent_key = jax.random.split(key)
        actor, critic, params = build_agent(
            fabric, cfg, observation_space, action_space, agent_key, state["agent"] if state else None
        )
        act_dim = int(np.prod(action_space.shape))
        target_entropy = -float(act_dim)
        action_scale = jnp.asarray(actor.action_scale, dtype=jnp.float32)
        action_bias = jnp.asarray(actor.action_bias, dtype=jnp.float32)

        if fabric.is_global_zero:
            save_configs(cfg, log_dir)

        aggregator = None
        if not MetricAggregator.disabled:
            aggregator = instantiate(cfg.metric.aggregator)

        buffer_size = cfg.buffer.size // total_num_envs if not cfg.dry_run else 1
        rb = ReplayBuffer(
            buffer_size,
            total_num_envs,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
            obs_keys=("observations",),
        )
        if state is not None and "rb" in state:
            rb = state["rb"]

        policy_steps_per_iter = int(total_num_envs)
        total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
        learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
        prefill_steps = learning_starts - int(learning_starts > 0)
        ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
        sample_next_obs = bool(cfg.buffer.sample_next_obs)
        start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
        if state is not None:
            ratio.load_state_dict(state["ratio"])
            cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
            # re-prefill window (coupled sac.py:145-148, reference sac.py:222-226):
            # shift learning_starts past the resume point so a resumed run —
            # in particular one without a restored buffer — refills from the env
            # before training instead of sampling a near-empty buffer
            learning_starts += start_iter
            prefill_steps += start_iter

        error: Dict[str, Any] = {}
        if two_process:
            opts = channel_options(cfg)
            data_q = BroadcastChannel(src=0, **opts)
            params_q = BroadcastChannel(src=1, **opts)
            trainer = None
            data_q.put({"player_world_size": world_size})  # geometry handshake
        else:
            data_q = queue.Queue(maxsize=1)
            params_q = queue.Queue(maxsize=1)
            trainer = threading.Thread(
                target=_trainer_loop,
                args=(fabric, cfg, actor, critic, params, target_entropy, data_q, params_q, error),
                kwargs={"resume_state": state},
                daemon=True,
                name="sac-learner",
            )
            trainer.start()

        act = ActPlacement(fabric, lambda p: p["actor"])
        act_on_cpu = act.on_cpu

        from functools import partial

        @partial(jax.jit, backend="cpu" if act_on_cpu else None)
        def act_fn(actor_params, obs: jax.Array, key):
            # PRNG chain advances inside the jitted program (un-jitted per-step
            # jax.random.split costs ~0.5 ms of host dispatch)
            key, step_key = jax.random.split(key)
            mean, std = actor.apply({"params": actor_params}, obs)
            actions, _ = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
            return actions, key

        act_params = act.view(params)
        params_host = jax.tree_util.tree_map(np.asarray, params)

        # replay hot path: the prefetcher overlaps host sampling with env stepping
        # and the learner's round; staging stays host-side (sharding=None) because
        # the data plane ships pickled host blocks the learner stages itself
        sampler = make_replay_sampler(
            rb,
            cfg.buffer.get("prefetch"),
            sample_kwargs=dict(
                batch_size=cfg.algo.per_rank_batch_size * world_size,
                sample_next_obs=sample_next_obs,
            ),
            uint8_keys=(),  # everything float32
            sharding=None,
            name="sac-dec-replay-prefetch",
        )
        telemetry.attach_sampler(sampler)
        opt_state_host: Optional[Any] = None
        key = act.place(key)

        policy_step = state["iter_num"] * cfg.env.num_envs if state is not None else 0
        last_log = state["last_log"] if state is not None else 0
        last_checkpoint = state["last_checkpoint"] if state is not None else 0
        cumulative_per_rank_gradient_steps = 0
        step_data: Dict[str, np.ndarray] = {}
        obs = envs.reset(seed=cfg.seed)[0]

        for iter_num in range(start_iter, total_iters + 1):
            policy_step += policy_steps_per_iter

            with timer("Time/env_interaction_time"):
                if iter_num <= learning_starts:
                    actions = envs.action_space.sample()
                else:
                    flat_obs = prepare_obs(fabric, obs, mlp_keys=mlp_keys, num_envs=total_num_envs)
                    actions, key = act_fn(act_params, flat_obs, key)
                    actions = np.asarray(actions)
                next_obs, rewards, terminated, truncated, infos = envs.step(
                    np.asarray(actions).reshape(envs.action_space.shape)
                )
                rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, -1)

            ep_info = infos.get("final_info", infos)
            if "episode" in ep_info:
                ep = ep_info["episode"]
                mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
                rews, lens = ep["r"][mask], ep["l"][mask]
                if len(rews) > 0:
                    telemetry.observe_episodes(rews, lens)
                    if aggregator and not aggregator.disabled:
                        aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                        aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
            final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
            if final_obs_arr is not None:
                for idx in range(total_num_envs):
                    if final_obs_arr[idx] is not None:
                        for k in mlp_keys:
                            real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])
            flat_real_next = np.concatenate(
                [real_next_obs[k].reshape(total_num_envs, -1) for k in mlp_keys], axis=-1
            ).astype(np.float32)

            step_data["terminated"] = np.asarray(terminated).reshape(1, total_num_envs, -1).astype(np.float32)
            step_data["truncated"] = np.asarray(truncated).reshape(1, total_num_envs, -1).astype(np.float32)
            step_data["actions"] = np.asarray(actions).reshape(1, total_num_envs, -1).astype(np.float32)
            step_data["observations"] = np.concatenate(
                [np.asarray(obs[k]).reshape(total_num_envs, -1) for k in mlp_keys], axis=-1
            ).astype(np.float32)[np.newaxis]
            if not sample_next_obs:
                step_data["next_observations"] = flat_real_next[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis]
            sampler.add(step_data, validate_args=cfg.buffer.validate_args)

            obs = next_obs

            # one preemption snapshot per iteration: the want_opt_state request,
            # the checkpoint block and the loop-exit break must agree on it (the
            # emergency checkpoint needs the opt state riding the weight plane)
            preempted = resilience.preempt_requested()

            if iter_num >= learning_starts:
                per_rank_gradient_steps = ratio((policy_step - prefill_steps + policy_steps_per_iter) / world_size)
                if per_rank_gradient_steps > 0:
                    with timer("Time/train_time"):
                        data = sampler.sample(per_rank_gradient_steps)
                        # data plane: ship the replay block to the learner (reference
                        # scatter, sac_decoupled.py:243-257) and BLOCK on the weight plane
                        want_opt_state = bool(
                            (
                                cfg.checkpoint.every > 0
                                and policy_step - last_checkpoint >= cfg.checkpoint.every
                            )
                            or cfg.dry_run
                            or (iter_num == total_iters and cfg.checkpoint.save_last)
                            or preempted
                        )
                        data_q.put((data, iter_num, want_opt_state))
                        msg = params_q.get()
                        if msg is None:
                            if "exc" in error:
                                raise error["exc"]
                            if two_process:
                                raise RuntimeError(
                                    "the learner process crashed mid-run (sent a weight-plane "
                                    "sentinel before the player finished); see its log"
                                )
                            break
                        params_host, opt_state_host, mean_losses, learn = msg
                        act_params = act.view(params_host)
                        cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                        telemetry.observe_train(per_rank_gradient_steps, mean_losses)
                        telemetry.observe_learn(learn)
                        if aggregator and not aggregator.disabled:
                            aggregator.update("Loss/value_loss", float(mean_losses[0]))
                            aggregator.update("Loss/policy_loss", float(mean_losses[1]))
                            aggregator.update("Loss/alpha_loss", float(mean_losses[2]))

            telemetry.step(policy_step)
            resilience.step(policy_step)
            if cfg.metric.log_level > 0 and (
                policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
            ):
                with timer("Time/logging_time"):
                    metrics_dict = aggregator.compute() if aggregator else {}
                    if logger is not None:
                        logger.log_metrics(metrics_dict, policy_step)
                        timers = timer.to_dict(reset=False)
                        if timers.get("Time/train_time", 0) > 0:
                            logger.log_metrics(
                                {"Time/sps_train": (policy_step - last_log) / max(timers["Time/train_time"], 1e-9)},
                                policy_step,
                            )
                        if timers.get("Time/env_interaction_time", 0) > 0:
                            logger.log_metrics(
                                {
                                    "Time/sps_env_interaction": (policy_step - last_log)
                                    / max(timers["Time/env_interaction_time"], 1e-9)
                                },
                                policy_step,
                            )
                    timer.to_dict(reset=True)
                    if aggregator:
                        aggregator.reset()
                last_log = policy_step

            # a preemption forces an out-of-cadence emergency checkpoint through
            # the same callback path, then exits the loop; the clean teardown
            # below forwards the shutdown to the trainer ranks over the data plane
            if (
                (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
                or cfg.dry_run
                or (iter_num == total_iters and cfg.checkpoint.save_last)
                or preempted
            ):
                last_checkpoint = policy_step
                ckpt_state = {
                    "agent": params_host,
                    "opt_state": opt_state_host,
                    "ratio": ratio.state_dict(),
                    "iter_num": iter_num * world_size,
                    "batch_size": cfg.algo.per_rank_batch_size * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                }
                ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
                # quiesce the prefetch worker so the pickled buffer (incl. its RNG
                # state) is not a torn mid-sample snapshot
                with sampler.lock, timer("Time/checkpoint_time"):
                    fabric.call(
                        "on_checkpoint_player",
                        ckpt_path=ckpt_path,
                        state=ckpt_state,
                        replay_buffer=rb if cfg.buffer.checkpoint else None,
                    )
                resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
            if preempted:
                break

        sampler.close()
        data_q.put(None)
        if trainer is not None:
            trainer.join(timeout=60)
        else:
            params_q.get()  # consume the learner's sentinel ack (lockstep pairing)
        _protocol_done = True
        if "exc" in error:
            raise error["exc"]

        envs.close()
        # an in-flight async (orbax) checkpoint write must land before teardown
        wait_for_checkpoint()
        if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
            with timer("Time/test_time"):
                test(actor.apply, jax.tree_util.tree_map(jnp.asarray, params_host["actor"]), fabric, cfg, log_dir)
        # closed AFTER the final test so the summary phases include eval time; an
        # exception path that skips this is flushed by cli.run_algorithm with
        # clean_exit=False
        telemetry.close(policy_step)
        if logger is not None:
            logger.finalize()
    except BaseException as e:
        # skip the release when the crash WAS a channel collective: the plane is
        # desynced and another lockstep collective would hang, not raise
        if two_process and not _protocol_done and not isinstance(e, ChannelError):
            try:
                # the channels are stateful: reuse the live instances when the
                # crash happened after their creation
                opts = channel_options(cfg)
                (data_q if data_q is not None else BroadcastChannel(src=0, **opts)).put(None)
                (params_q if params_q is not None else BroadcastChannel(src=1, **opts)).get()
            except Exception:
                pass
        raise
