"""SAC evaluation entrypoint (reference: sheeprl/algos/sac/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["sac", "sac_decoupled"])
def evaluate(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logdir = cfg.get("log_dir", "logs/evaluation")
    env = make_env(cfg, cfg.seed, 0, logdir, "test")()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    env.close()
    actor, critic, params = build_agent(
        fabric, cfg, observation_space, action_space, jax.random.PRNGKey(cfg.seed), state["agent"] if state else None
    )
    test(actor.apply, params["actor"], fabric, cfg, logdir)
