"""SAC-family serving extractor (sac / sac_decoupled / droq): continuous-control
MLP actors. Per-session state is the PRNG key alone; with ``serve.greedy=true``
(default) the served action is the squashed mean — the exact computation of
``sac.utils.test``."""

from __future__ import annotations

from typing import Any, Callable, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import greedy_action, squash_and_logprob
from sheeprl_tpu.serve.policy import ServePolicy, space_obs_spec
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_serve_policy


def _sac_like_serve_policy(fabric, cfg, state, build_agent: Callable) -> ServePolicy:
    env = make_env(cfg, cfg.seed, 0, None, "serve-probe")()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("SAC-family serving requires a continuous (Box) action space")
    action_shape = tuple(int(s) for s in action_space.shape)
    env.close()

    actor, _critic, params = build_agent(
        fabric,
        cfg,
        observation_space,
        action_space,
        jax.random.PRNGKey(cfg.seed),
        state["agent"] if state else None,
    )
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    greedy = bool((cfg.get("serve") or {}).get("greedy", True))
    action_scale = jnp.asarray(actor.action_scale, jnp.float32).reshape(-1)
    action_bias = jnp.asarray(actor.action_bias, jnp.float32).reshape(-1)

    def init_slot(params, key):
        return {"key": key}

    def step_slot(params, carry, obs):
        key, step_key = jax.random.split(carry["key"])
        flat = jnp.concatenate(
            [obs[k].astype(jnp.float32).reshape(-1) for k in mlp_keys], axis=-1
        )
        mean, std = actor.apply({"params": params["actor"]}, flat)
        if greedy:
            action = greedy_action(mean, action_scale, action_bias)
        else:
            action, _ = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
        return action.reshape(action_shape).astype(jnp.float32), {"key": key}

    return ServePolicy(
        algo=str(cfg.algo.name),
        params=params,
        init_slot=init_slot,
        step_slot=step_slot,
        obs_spec=space_obs_spec(observation_space, mlp_keys),
        action_shape=action_shape,
        action_dtype=np.float32,
        meta={"family": "sac", "greedy": greedy, "recurrent": False},
    )


@register_serve_policy(algorithms=["sac", "sac_decoupled"])
def get_serve_policy(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> ServePolicy:
    from sheeprl_tpu.algos.sac.agent import build_agent

    return _sac_like_serve_policy(fabric, cfg, state, build_agent)


@register_serve_policy(algorithms=["droq"])
def get_serve_policy_droq(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> ServePolicy:
    from sheeprl_tpu.algos.droq.agent import build_agent

    return _sac_like_serve_policy(fabric, cfg, state, build_agent)
