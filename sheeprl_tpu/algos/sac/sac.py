"""SAC, coupled training (capability parity with sheeprl/algos/sac/sac.py:85-427).

TPU-native structure:
- the act path is a tiny jitted sampler pinned to the host CPU backend (envs are
  host-side; the reference pays a per-step ``.cpu().numpy()`` sync, sac.py:259-262);
- each iteration's ``per_rank_gradient_steps`` critic/actor/alpha updates run as ONE
  jitted device program: the replay batch is sampled as ``[G, B, ...]`` on the host,
  uploaded once, and a ``lax.scan`` walks the G gradient steps (the replay-ratio
  governor ``Ratio`` stays host-side, reference sac.py:301-309);
- under dp the batch axis is sharded over the mesh ``data`` axis and XLA inserts the
  gradient psum (replacing DDP allreduce + the explicit log-alpha all_reduce at
  reference sac.py:74);
- target-critic EMA is a pure pytree lerp inside the same program (reference
  qfs_target_ema, agent.py:262-268).
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.sac.agent import build_agent, squash_and_logprob
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.analysis.programs import register_fused_program
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, BenchWindow, Ratio, save_configs


def build_optimizers(cfg) -> Dict[str, Any]:
    """The three SAC optimizers (reference sac.py:151-173) — ONE construction
    shared by the coupled loop, the decoupled trainer/service learner
    (sac_decoupled._build_sac_train) and the AOT program registry."""
    return {
        "actor": instantiate(cfg.algo.actor.optimizer),
        "critic": instantiate(cfg.algo.critic.optimizer),
        "alpha": instantiate(cfg.algo.alpha.optimizer),
    }


def init_opt_state(txs: Dict[str, Any], params) -> Dict[str, Any]:
    return {
        "actor": txs["actor"].init(params["actor"]),
        "critic": txs["critic"].init(params["critic"]),
        "alpha": txs["alpha"].init(params["log_alpha"]),
    }


def make_train_body(cfg, actor, critic, target_entropy, policy_steps_per_iter, txs=None):
    """The UNJITTED fused multi-gradient-step SAC update: a ``lax.scan`` over
    the ``[G, B, ...]`` replay block running critic -> EMA -> actor -> alpha
    per step (reference train(), sac.py:32-81). :func:`make_train_phase` wraps
    it as the host loop's standalone donated program; the fully fused
    ``sac_anakin`` topology (``algos/sac/anakin.py``) inlines this same body
    after its on-device rollout+ring stages — ONE update implementation for
    every SAC topology and the AOT contract registry."""
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    num_critics = int(cfg.algo.critic.n)
    target_period = cfg.algo.critic.target_network_frequency // int(policy_steps_per_iter) + 1
    action_scale = jnp.asarray(actor.action_scale, dtype=jnp.float32)
    action_bias = jnp.asarray(actor.action_bias, dtype=jnp.float32)
    txs = txs if txs is not None else build_optimizers(cfg)
    actor_tx, critic_tx, alpha_tx = txs["actor"], txs["critic"], txs["alpha"]
    # compile the Learn/* stats only when the telemetry learning plane is on:
    # the off path lowers byte-identically to the pre-plane program
    learn_on = learn_stats.enabled(cfg)

    def critic_loss_fn(critic_params, other, batch, step_key):
        next_obs = batch["next_observations"]
        mean, std = actor.apply({"params": other["actor"]}, next_obs)
        next_actions, next_logprobs = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
        target_q = critic.apply({"params": other["target_critic"]}, next_obs, next_actions)
        alpha = jnp.exp(other["log_alpha"])
        min_target = jnp.min(target_q, axis=-1, keepdims=True) - alpha * next_logprobs
        next_qf_value = batch["rewards"] + (1 - batch["terminated"]) * gamma * min_target
        qf_values = critic.apply({"params": critic_params}, batch["observations"], batch["actions"])
        loss = critic_loss(qf_values, jax.lax.stop_gradient(next_qf_value), num_critics)
        # aux for the learn-stats block: Q statistics + the per-sample TD error
        # (value_overestimation / td-quantile detectors read them per window)
        return loss, (qf_values, qf_values - next_qf_value)

    def actor_loss_fn(actor_params, other, batch, step_key):
        mean, std = actor.apply({"params": actor_params}, batch["observations"])
        actions, logprobs = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
        qf_values = critic.apply({"params": other["critic"]}, batch["observations"], actions)
        min_qf = jnp.min(qf_values, axis=-1, keepdims=True)
        alpha = jnp.exp(jax.lax.stop_gradient(other["log_alpha"]))
        return policy_loss(alpha, logprobs, min_qf), logprobs

    def alpha_loss_fn(log_alpha, logprobs):
        return entropy_loss(log_alpha, jax.lax.stop_gradient(logprobs), target_entropy)

    def train_phase(params, opt_state, data, iter_num, train_key):
        """scan over the [G, B, ...] gradient-step axis: critic -> EMA -> actor -> alpha
        (one fused device program per iteration; reference train(), sac.py:32-81)."""
        # reference gates EMA on the iteration counter (sac.py:57-59 with update=iter_num)
        do_ema = (iter_num % target_period) == 0

        def step(carry, inp):
            params, opt_state = carry
            batch, k = inp
            k_critic, k_actor = jax.random.split(k)

            (qf_loss, (qf_values, td_error)), qf_grads = jax.value_and_grad(
                critic_loss_fn, has_aux=True
            )(params["critic"], params, batch, k_critic)
            c_updates, new_copt = critic_tx.update(qf_grads, opt_state["critic"], params["critic"])
            params = {**params, "critic": optax.apply_updates(params["critic"], c_updates)}
            opt_state = {**opt_state, "critic": new_copt}
            params = {
                **params,
                "target_critic": jax.tree_util.tree_map(
                    lambda t, c: jnp.where(do_ema, t * (1 - tau) + c * tau, t),
                    params["target_critic"],
                    params["critic"],
                ),
            }

            (a_loss, logprobs), a_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
                params["actor"], params, batch, k_actor
            )
            a_updates, new_aopt = actor_tx.update(a_grads, opt_state["actor"], params["actor"])
            params = {**params, "actor": optax.apply_updates(params["actor"], a_updates)}
            opt_state = {**opt_state, "actor": new_aopt}

            al_loss, al_grads = jax.value_and_grad(alpha_loss_fn)(params["log_alpha"], logprobs)
            al_updates, new_alopt = alpha_tx.update(al_grads, opt_state["alpha"], params["log_alpha"])
            params = {**params, "log_alpha": optax.apply_updates(params["log_alpha"], al_updates)}
            opt_state = {**opt_state, "alpha": new_alopt}

            # device-side training-health block (utils/learn_stats.py): scalars
            # only, computed from values already materialized by the update
            learn = learn_stats.maybe(learn_on, lambda: {
                **learn_stats.group_stats(
                    "critic",
                    grads=qf_grads,
                    updates=c_updates,
                    params=params["critic"],
                    opt_state=new_copt,
                ),
                **learn_stats.group_stats(
                    "actor",
                    grads=a_grads,
                    updates=a_updates,
                    params=params["actor"],
                    opt_state=new_aopt,
                ),
                **learn_stats.group_stats("alpha", grads=al_grads),
                **learn_stats.value_stats(qf_values, prefix="q"),
                **learn_stats.td_quantiles(td_error),
                **learn_stats.entropy_stats(-logprobs),
                "Learn/alpha": jnp.exp(params["log_alpha"]).reshape(()),
                "Learn/loss/critic": qf_loss,
                "Learn/loss/actor": a_loss,
                "Learn/loss/alpha": al_loss,
            })
            return (params, opt_state), (jnp.stack([qf_loss, a_loss, al_loss]), learn)

        G = data["rewards"].shape[0]
        keys = jax.random.split(train_key, G)
        (params, opt_state), (losses, learn) = jax.lax.scan(step, (params, opt_state), (data, keys))
        return params, opt_state, losses.mean(axis=0), learn_stats.reduce_stacked(learn)

    return train_phase


def make_train_phase(cfg, actor, critic, target_entropy, policy_steps_per_iter, txs=None, jit_kwargs=None):
    """Jit :func:`make_train_body` as the host loop's standalone per-iteration
    device program. Shared verbatim by the coupled loop, the decoupled
    trainer/service learner and the AOT contract registry — the program that
    lowers in the gate is the program that trains.

    donate_argnums: XLA reuses the params/opt-state buffers in place instead of
    copying the whole train state every round (callers always rebind to the
    returned trees, so the invalidated inputs are never read again).
    ``jit_kwargs`` carries the multi-device ``out_shardings`` pin — without it
    GSPMD propagation may re-scatter small state leaves on output, silently
    degrading the donation aliasing (the PR 8 residual; parallel/sharding.py
    build_state_shardings). ``policy_steps_per_iter`` sets the target-EMA
    period in iterations, exactly as before."""
    body = make_train_body(cfg, actor, critic, target_entropy, policy_steps_per_iter, txs=txs)
    return partial(jax.jit, donate_argnums=(0, 1), **(jit_kwargs or {}))(body)


@register_fused_program(
    "sac.train_phase",
    min_donated=2,
    doc="fused SAC multi-gradient-step update (critic -> EMA -> actor -> alpha scan)",
)
def _aot_train_program():
    """Tiny MLP SAC agent through the loop's own factory."""
    from sheeprl_tpu.analysis.programs import tiny_fabric
    from sheeprl_tpu.config import compose

    cfg = compose(
        [
            "exp=sac",
            "env=dummy",
            "fabric.accelerator=cpu",
            "env.num_envs=2",
            "env.capture_video=False",
            "algo.hidden_size=16",
            "algo.per_rank_batch_size=4",
            "buffer.memmap=False",
            "metric.log_level=0",
            # lower the GROWN program (Learn/* stats compile in under telemetry)
            "metric.telemetry.enabled=true",
        ]
    )
    fabric = tiny_fabric()
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (8,), np.float32)})
    action_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
    actor, critic, params = build_agent(fabric, cfg, obs_space, action_space, jax.random.PRNGKey(0), None)
    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    train_phase = make_train_phase(
        cfg, actor, critic, target_entropy=-2.0, policy_steps_per_iter=2, txs=txs
    )
    G, B = 1, int(cfg.algo.per_rank_batch_size)
    rng = np.random.default_rng(0)
    data = {
        "observations": rng.normal(size=(G, B, 8)).astype(np.float32),
        "next_observations": rng.normal(size=(G, B, 8)).astype(np.float32),
        "actions": rng.normal(size=(G, B, 2)).astype(np.float32),
        "rewards": rng.normal(size=(G, B, 1)).astype(np.float32),
        "terminated": np.zeros((G, B, 1), np.float32),
    }
    args = (params, opt_state, data, jnp.asarray(1), np.asarray(jax.random.PRNGKey(1)))
    return train_phase, args


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    total_num_envs = int(cfg.env.num_envs * world_size)
    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * total_num_envs + i,
                rank * total_num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(total_num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}. "
                f"Provided environment: {cfg.env.id}"
            )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    mlp_keys = cfg.algo.mlp_keys.encoder

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    actor, critic, params = build_agent(
        fabric, cfg, observation_space, action_space, agent_key, state["agent"] if state else None
    )
    act_dim = int(np.prod(action_space.shape))
    target_entropy = -float(act_dim)
    action_scale = jnp.asarray(actor.action_scale, dtype=jnp.float32)
    action_bias = jnp.asarray(actor.action_bias, dtype=jnp.float32)

    # three optimizers, one per parameter group (reference sac.py:151-173) —
    # shared construction with the decoupled learner and the AOT registry
    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    if state is not None:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // total_num_envs if not cfg.dry_run else 1
    rb = ReplayBuffer(
        buffer_size,
        total_num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=("observations",),
    )
    if state is not None and "rb" in state:
        rb = state["rb"]

    # counters (reference sac.py:200-226)
    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(total_num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state is not None:
        ratio.load_state_dict(state["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    # ---------------- jitted programs ----------------
    sample_next_obs = bool(cfg.buffer.sample_next_obs)

    act = ActPlacement(fabric, lambda p: p["actor"])
    act_on_cpu = act.on_cpu

    @partial(jax.jit, backend="cpu" if act_on_cpu else None)
    def act_fn(actor_params, obs: jax.Array, key):
        # PRNG chain advances inside the jitted program (un-jitted per-step
        # jax.random.split costs ~0.5 ms of host dispatch)
        key, step_key = jax.random.split(key)
        mean, std = actor.apply({"params": actor_params}, obs)
        actions, _ = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
        return actions, key

    # the fused train program — ONE factory (make_train_phase) shared with the
    # decoupled trainer/service learner and the AOT contract registry, so the
    # program `sheeprl.py lint --aot` lowers is the program this loop runs.
    # out_shardings pins the state outputs on multi-device meshes (replicated on
    # dp) — see make_train_phase's donation note.
    from sheeprl_tpu.parallel.sharding import build_state_shardings

    # extra_outputs=2: the losses vector AND the Learn/* stats block
    _state_shardings = build_state_shardings(fabric, params, opt_state, extra_outputs=2)
    _train_jit_kwargs = (
        {"out_shardings": tuple(_state_shardings)} if _state_shardings is not None else {}
    )
    train_phase = make_train_phase(
        cfg,
        actor,
        critic,
        target_entropy,
        policy_steps_per_iter,
        txs=txs,
        jit_kwargs=_train_jit_kwargs,
    )

    if world_size > 1:
        params = fabric.replicate_pytree(params)
        opt_state = fabric.replicate_pytree(opt_state)
    act_params = act.view(params)
    key = act.place(key)

    # replay hot path: async prefetcher (sampling + sharded staging off-thread) or
    # the exact inline path when buffer.prefetch.enabled=false
    sampler = make_replay_sampler(
        rb,
        cfg.buffer.get("prefetch"),
        sample_kwargs=dict(
            batch_size=cfg.algo.per_rank_batch_size * world_size,
            sample_next_obs=sample_next_obs,
        ),
        uint8_keys=(),  # everything float32
        sharding=fabric.sharding(None, "data") if world_size > 1 else None,
        name="sac-replay-prefetch",
    )
    telemetry.attach_sampler(sampler)

    # ---------------- main loop ----------------
    cumulative_per_rank_gradient_steps = 0
    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]

    # Optional steady-state measurement window for bench.py (see bench.py docstring)
    bench = BenchWindow()

    for iter_num in range(start_iter, total_iters + 1):
        bench.maybe_start(policy_step, params)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                flat_obs = prepare_obs(fabric, obs, mlp_keys=mlp_keys, num_envs=total_num_envs)
                actions, key = act_fn(act_params, flat_obs, key)
                actions = np.asarray(actions)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
            rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, -1)

        ep_info = infos.get("final_info", infos)
        if "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        # real next obs for done envs (reference sac.py:281-289); the transition
        # assembly + buffer add is rollout work — timed as env interaction like
        # the dreamer loops, so phase attribution has no unnamed rollout gap
        with timer("Time/env_interaction_time"):
            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
            final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
            if final_obs_arr is not None:
                for idx in range(total_num_envs):
                    if final_obs_arr[idx] is not None:
                        for k in mlp_keys:
                            real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])
            flat_real_next = np.concatenate(
                [real_next_obs[k].reshape(total_num_envs, -1) for k in mlp_keys], axis=-1
            ).astype(np.float32)

            step_data["terminated"] = np.asarray(terminated).reshape(1, total_num_envs, -1).astype(np.float32)
            step_data["truncated"] = np.asarray(truncated).reshape(1, total_num_envs, -1).astype(np.float32)
            step_data["actions"] = actions.reshape(1, total_num_envs, -1).astype(np.float32)
            step_data["observations"] = np.concatenate(
                [np.asarray(obs[k]).reshape(total_num_envs, -1) for k in mlp_keys], axis=-1
            ).astype(np.float32)[np.newaxis]
            if not sample_next_obs:
                step_data["next_observations"] = flat_real_next[np.newaxis]
            step_data["rewards"] = rewards[np.newaxis]
            sampler.add(step_data, validate_args=cfg.buffer.validate_args)

        obs = next_obs

        # train (reference sac.py:299-324): Ratio decides G; one upload, one program
        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio((policy_step - prefill_steps + policy_steps_per_iter) / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(per_rank_gradient_steps)
                    key, train_key = jax.random.split(key)
                    # one-shot injected learning pathology (resilience.fault=
                    # lr_spike): identity unless the fault armed this iteration
                    params = apply_armed_learn_fault(params)
                    params, opt_state, mean_losses, learn = train_phase(
                        params, opt_state, data, jnp.asarray(iter_num), np.asarray(train_key)
                    )
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    act_params = act.view(params)
                    telemetry.observe_train(per_rank_gradient_steps, mean_losses)
                    telemetry.observe_learn(learn)
                    if telemetry.wants_program("train_phase"):
                        # post-call registration: params/opt_state are the REBOUND
                        # outputs (the donated inputs are dead), and registration
                        # abstracts to avals anyway
                        telemetry.register_program(
                            "train_phase",
                            train_phase,
                            (params, opt_state, data, jnp.asarray(iter_num), np.asarray(train_key)),
                            units=per_rank_gradient_steps,
                        )
                    if aggregator and not aggregator.disabled:
                        losses_np = np.asarray(mean_losses)
                        aggregator.update("Loss/value_loss", losses_np[0])
                        aggregator.update("Loss/policy_loss", losses_np[1])
                        aggregator.update("Loss/alpha_loss", losses_np[2])

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (policy_step - last_log) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (policy_step - last_log)
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop (snapshot the flag once so the
        # save and the break can never disagree about it)
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            # quiesce the prefetch worker so the pickled buffer (incl. its RNG
            # state) is not a torn mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    bench.finish(policy_step, params)
    sampler.close()
    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(actor.apply, params["actor"], fabric, cfg, log_dir)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
