"""SAC, Anakin topology: on-device envs + device-resident replay ring, with
rollout, ring write/sample and the gradient phase fused into one donated jitted
program over the mesh (see ``algos/sac/anakin.py`` for the architecture;
``algos/sac/sac.py`` is the host-env reference semantics)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.sac.anakin import run_sac_anakin
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    run_sac_anakin(fabric, cfg)
