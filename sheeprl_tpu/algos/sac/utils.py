"""SAC helpers: metric whitelist, obs preparation, greedy test rollout
(reference: sheeprl/algos/sac/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(
    fabric, obs: Dict[str, np.ndarray], *, mlp_keys: Sequence[str] = (), num_envs: int = 1, **_: Any
) -> jax.Array:
    """Concatenate the mlp-key observations into one flat float array
    [num_envs, obs_dim] (reference utils.py:prepare_obs)."""
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        return jnp.concatenate(
            [np.asarray(obs[k], dtype=np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
        )


def test(actor_apply, params, fabric, cfg, log_dir: str) -> None:
    """Greedy (mean-action) single-env rollout logging Test/cumulative_reward
    (reference utils.py:test)."""
    from sheeprl_tpu.algos.sac.agent import greedy_action
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, None, 0, log_dir, "test", vector_env_idx=0)()
    action_scale = (env.action_space.high - env.action_space.low) / 2.0
    action_bias = (env.action_space.high + env.action_space.low) / 2.0
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    while not done:
        jobs = prepare_obs(fabric, obs, mlp_keys=cfg.algo.mlp_keys.encoder)
        mean, _ = actor_apply({"params": params}, jobs)
        actions = np.asarray(greedy_action(mean, action_scale, action_bias))
        obs, reward, terminated, truncated, _ = env.step(actions.reshape(env.action_space.shape))
        done = bool(terminated) or bool(truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None):
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
