"""Plan2Explore (Dreamer-V1 backbone) agent (reference sheeprl/algos/p2e_dv1/agent.py):
DV1 world model + disagreement ensemble predicting the next *observation embedding*
+ exploration actor/critic (no target network)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v1.agent import DV1Agent
from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as build_dv1_agent
from sheeprl_tpu.algos.p2e_dv3.agent import EnsembleHeads


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV1Agent, EnsembleHeads, Dict[str, Any]]:
    k_dv1, k_expl, k_ens, k_crit = jax.random.split(key, 4)
    agent, dv1_params = build_dv1_agent(fabric, actions_dim, is_continuous, cfg, obs_space, k_dv1)

    latent = jnp.zeros((1, agent.latent_state_size), jnp.float32)
    actor_exploration_params = agent.actor.init(k_expl, latent)["params"]
    critic_exploration_params = agent.critic.init(k_crit, latent)["params"]

    # the embedding dim equals the encoder output: probe it
    dummy_obs = {}
    for k in tuple(cfg.algo.cnn_keys.encoder) + tuple(cfg.algo.mlp_keys.encoder):
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    embedded = agent.encoder.apply({"params": dv1_params["world_model"]["encoder"]}, dummy_obs)
    embedding_dim = int(embedded.shape[-1])

    ens_cfg = cfg.algo.ensembles
    ensembles = EnsembleHeads(
        n=int(ens_cfg.n),
        units=ens_cfg.dense_units,
        n_layers=ens_cfg.mlp_layers,
        output_dim=embedding_dim,
        activation=ens_cfg.dense_act,
        dtype=fabric.compute_dtype,
    )
    act_dim = int(np.sum(actions_dim))
    ens_in = jnp.zeros((1, agent.latent_state_size + act_dim), jnp.float32)
    ensembles_params = ensembles.init(k_ens, ens_in)["params"]

    params = {
        "world_model": dv1_params["world_model"],
        "actor_task": dv1_params["actor"],
        "critic_task": dv1_params["critic"],
        "actor_exploration": actor_exploration_params,
        "critic_exploration": critic_exploration_params,
        "ensembles": ensembles_params,
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    return agent, ensembles, params


def player_params(params: Dict[str, Any], actor_type: str) -> Dict[str, Any]:
    return {
        "world_model": params["world_model"],
        "actor": params["actor_exploration"] if actor_type == "exploration" else params["actor_task"],
    }
