"""Plan2Explore DV1 — finetuning phase (capability parity with
sheeprl/algos/p2e_dv1/p2e_dv1_finetuning.py): resume the exploration checkpoint's
world model and task heads, optionally inherit the exploration replay buffer, act
with the exploration actor during the prefill, then train the task heads with the
standard Dreamer-V1 program."""

from __future__ import annotations

import pathlib
from typing import Any, Dict

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v1 import dreamer_v1 as dv1
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm()
def main(fabric, cfg: Dict[str, Any], exploration_cfg: Dict[str, Any]):
    ckpt_path = pathlib.Path(cfg.checkpoint.exploration_ckpt_path)
    resume = cfg.checkpoint.resume_from is not None
    state = fabric.load(pathlib.Path(cfg.checkpoint.resume_from) if resume else ckpt_path)

    for k in (
        "gamma", "lmbda", "horizon", "dense_units", "mlp_layers", "dense_act", "cnn_act",
        "world_model", "actor", "critic", "cnn_keys", "mlp_keys",
    ):
        if k in exploration_cfg.algo:
            cfg.algo[k] = exploration_cfg.algo[k]
    cfg.env.clip_rewards = exploration_cfg.env.clip_rewards
    if cfg.buffer.get("load_from_exploration", False) and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs

    agent_state = jax.tree_util.tree_map(jnp.asarray, state["agent"])
    dv1_state = dict(state)
    exploration_actor_params = None
    if "actor_task" in agent_state:
        # p2e layout (exploration checkpoint) → remap to DV1 layout
        dv1_state["agent"] = {
            "world_model": agent_state["world_model"],
            "actor": agent_state["actor_task"],
            "critic": agent_state["critic_task"],
        }
        if cfg.algo.player.actor_type == "exploration":
            exploration_actor_params = agent_state["actor_exploration"]
    else:
        # already DV1 layout: resuming an interrupted finetuning checkpoint
        dv1_state["agent"] = agent_state
    if not resume:
        for k in ("iter_num", "last_log", "last_checkpoint"):
            dv1_state[k] = 0
        dv1_state["batch_size"] = cfg.algo.per_rank_batch_size * fabric.world_size
        dv1_state.pop("opt_state", None)
        dv1_state.pop("ratio", None)
        if not cfg.buffer.get("load_from_exploration", False):
            dv1_state.pop("rb", None)

    _orig_load = fabric.load
    fabric.load = lambda path: dv1_state
    cfg.checkpoint.resume_from = cfg.checkpoint.resume_from or str(ckpt_path)
    try:
        dv1.main(fabric, cfg, exploration_actor_params=exploration_actor_params)
    finally:
        fabric.load = _orig_load
