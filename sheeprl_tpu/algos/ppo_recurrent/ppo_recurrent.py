"""Recurrent PPO, coupled training (capability parity with
sheeprl/algos/ppo_recurrent/ppo_recurrent.py:33-524).

TPU-native structure:
- the act path is one jitted encoder→LSTM-step→actor program with an explicit
  (hx, cx) carry per env, reset on done (reference keeps a stateful module and pays
  per-step ``.cpu()`` syncs);
- after the rollout, episodes are chopped into ``per_rank_sequence_length`` chunks and
  padded host-side (numpy), then the whole optimization — update_epochs × sequence
  minibatches, each a masked ``lax.scan`` LSTM unroll — runs as ONE jitted device
  program (the reference packs/pads with torch.nn.utils.rnn per minibatch,
  ppo_recurrent.py:407-447);
- the padded sequence-count axis is bucketed to powers of two so XLA recompiles a
  bounded number of program variants;
- under dp the sequence axis is sharded over the mesh ``data`` axis.
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict, List

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.agent import policy_output
from sheeprl_tpu.algos.ppo.utils import normalize_obs
from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent
from sheeprl_tpu.algos.ppo_recurrent.utils import test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, gae, polynomial_decay, save_configs


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _masked_mean(x: jax.Array, mask: jax.Array) -> jax.Array:
    return jnp.sum(x * mask) / jnp.maximum(jnp.sum(mask), 1.0)


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    initial_ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef = float(cfg.algo.clip_coef)

    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    if cfg.algo.rollout_steps % cfg.algo.per_rank_sequence_length != 0:
        raise ValueError(
            f"rollout_steps ({cfg.algo.rollout_steps}) must be a multiple of "
            f"per_rank_sequence_length ({cfg.algo.per_rank_sequence_length})"
        )

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    total_num_envs = int(cfg.env.num_envs * world_size)
    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * total_num_envs + i,
                rank * total_num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(total_num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder == []:
        raise RuntimeError(
            "You should specify at least one CNN or MLP key for the encoder: "
            "`algo.cnn_keys.encoder=[rgb]` or `algo.mlp_keys.encoder=[state]`"
        )
    obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
    cnn_keys = cfg.algo.cnn_keys.encoder

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )
    act_dim = int(np.sum(actions_dim))

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state["agent"])

    # counters
    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(total_num_envs * cfg.algo.rollout_steps)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1

    lr = cfg.algo.optimizer.lr
    if cfg.algo.anneal_lr:
        lr = optax.linear_schedule(
            init_value=lr,
            end_value=0.0,
            transition_steps=total_iters * cfg.algo.update_epochs * max(1, cfg.algo.per_rank_num_batches),
        )
    tx = instantiate(cfg.algo.optimizer, lr=lr)
    if cfg.algo.max_grad_norm > 0.0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.algo.max_grad_norm), tx)
    opt_state = tx.init(params)
    if state is not None and "optimizer" in state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["optimizer"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb = ReplayBuffer(
        cfg.algo.rollout_steps,
        total_num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=obs_keys,
    )

    # ---------------- jitted programs ----------------
    loss_reduction = cfg.algo.loss_reduction
    vf_coef = float(cfg.algo.vf_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_advantages = bool(cfg.algo.normalize_advantages)
    num_batches = max(1, int(cfg.algo.per_rank_num_batches))
    sl = int(cfg.algo.per_rank_sequence_length)

    act = ActPlacement(fabric)
    act_on_cpu = act.on_cpu

    @partial(jax.jit, backend="cpu" if act_on_cpu else None)
    def policy_step_fn(params, obs, prev_actions, hx, cx, key):
        # PRNG chain advances inside the jitted program (saves ~0.5 ms/step)
        key, step_key = jax.random.split(key)
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        norm = {k: v[None].astype(jnp.float32) for k, v in norm.items()}
        pre_dist, values, (hx, cx) = agent.forward(params, norm, prev_actions[None], hx, cx)
        out = policy_output(
            [p[0] for p in pre_dist], values[0], step_key, actions_dim, is_continuous
        )
        if is_continuous:
            real_actions = out["actions"]
        else:
            split = jnp.split(out["actions"], np.cumsum(actions_dim)[:-1].tolist(), axis=-1)
            real_actions = jnp.stack([s.argmax(axis=-1) for s in split], axis=-1)
        return out, real_actions, hx, cx, key

    @partial(jax.jit, backend="cpu" if act_on_cpu else None)
    def get_values(params, obs, prev_actions, hx, cx):
        norm = normalize_obs(obs, cnn_keys, obs_keys)
        norm = {k: v[None].astype(jnp.float32) for k, v in norm.items()}
        _, values, _ = agent.forward(params, norm, prev_actions[None], hx, cx)
        return values[0]

    # compile the Learn/* stats only when the telemetry learning plane is on
    learn_on = learn_stats.enabled(cfg)

    def loss_fn(params, batch, clip_coef, ent_coef):
        mask = batch["mask"]  # [sl, B, 1]
        norm_obs = normalize_obs(batch, cnn_keys, obs_keys)
        pre_dist, values, _ = agent.forward(
            params,
            norm_obs,
            batch["prev_actions"],
            batch["prev_hx"][0],
            batch["prev_cx"][0],
            mask=mask.astype(bool),
        )
        out = policy_output(
            pre_dist, values, jax.random.PRNGKey(0), actions_dim, is_continuous, actions=batch["actions"]
        )
        advantages = batch["advantages"]
        if normalize_advantages:
            m = _masked_mean(advantages, mask)
            var = _masked_mean(jnp.square(advantages - m), mask)
            advantages = (advantages - m) / (jnp.sqrt(var) + 1e-8)
        logratio = out["logprob"] - batch["logprobs"]
        ratio = jnp.exp(logratio)
        pg1 = -advantages * ratio
        pg2 = -advantages * jnp.clip(ratio, 1 - clip_coef, 1 + clip_coef)
        pg_loss = _masked_mean(jnp.maximum(pg1, pg2), mask)
        if clip_vloss:
            v_pred = batch["values"] + jnp.clip(out["values"] - batch["values"], -clip_coef, clip_coef)
        else:
            v_pred = out["values"]
        v_loss = _masked_mean(jnp.square(v_pred - batch["returns"]), mask)
        ent_loss = -_masked_mean(out["entropy"], mask)
        loss = pg_loss + vf_coef * v_loss + ent_coef * ent_loss
        # learn-stats aux (scalars only; padding masked out of the means)
        stats = learn_stats.maybe(learn_on, lambda: {
            **learn_stats.value_stats(jax.lax.stop_gradient(out["values"])),
            **learn_stats.td_quantiles(jax.lax.stop_gradient(batch["returns"] - out["values"])),
            "Learn/entropy": jax.lax.stop_gradient(_masked_mean(out["entropy"], mask)),
        })
        return loss, (pg_loss, v_loss, ent_loss, stats)

    @jax.jit
    def train_phase(params, opt_state, seqs, train_key, clip_coef, ent_coef):
        """update_epochs × sequence-minibatches, fused. ``seqs`` is the padded
        [sl, N, ...] block (N bucketed to a power of two)."""
        N = seqs["mask"].shape[1]
        bs = max(1, N // num_batches)
        nmb = N // bs

        def epoch_body(carry, epoch_key):
            params, opt_state = carry
            perm = jax.random.permutation(epoch_key, N)
            mb_idx = perm[: nmb * bs].reshape(nmb, bs)

            def mb_body(carry, idx):
                params, opt_state = carry
                batch = {k: jnp.take(v, idx, axis=1) for k, v in seqs.items()}
                grads, (pg, vl, ent, stats) = jax.grad(loss_fn, has_aux=True)(
                    params, batch, clip_coef, ent_coef
                )
                # a minibatch drawn entirely from padding has exactly-zero gradients
                # but would still advance Adam moments/schedule — skip it
                has_real = jnp.sum(batch["mask"]) > 0
                new_updates, new_opt = tx.update(grads, opt_state, params)
                pick = lambda n, o: jnp.where(has_real, n, o)
                new_params = optax.apply_updates(params, new_updates)
                params = jax.tree_util.tree_map(pick, new_params, params)
                opt_state = jax.tree_util.tree_map(pick, new_opt, opt_state)
                learn = learn_stats.maybe(learn_on, lambda: {
                    **stats,
                    **learn_stats.group_stats(
                        "policy",
                        grads=grads,
                        updates=new_updates,
                        params=params,
                        opt_state=opt_state,
                        clip=float(cfg.algo.max_grad_norm or 0) or None,
                    ),
                    "Learn/loss/policy": pg,
                    "Learn/loss/value": vl,
                    "Learn/loss/entropy": ent,
                })
                return (params, opt_state), (jnp.stack([pg, vl, ent]), learn)

            (params, opt_state), (losses, learn) = jax.lax.scan(mb_body, (params, opt_state), mb_idx)
            return (params, opt_state), (losses.mean(axis=0), learn)

        epoch_keys = jax.random.split(train_key, cfg.algo.update_epochs)
        (params, opt_state), (losses, learn) = jax.lax.scan(epoch_body, (params, opt_state), epoch_keys)
        return params, opt_state, losses.mean(axis=0), learn_stats.reduce_stacked(learn)

    if world_size > 1:
        params = fabric.replicate_pytree(params)
        opt_state = fabric.replicate_pytree(opt_state)
    act_params = act.view(params)
    key = act.place(key)

    # ---------------- main loop ----------------
    ent_coef = initial_ent_coef
    clip_coef = initial_clip_coef

    step_data: Dict[str, np.ndarray] = {}
    next_obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(next_obs[k])[np.newaxis]
    prev_actions = np.zeros((total_num_envs, act_dim), np.float32)
    hx = np.zeros((total_num_envs, agent.rnn_hidden_size), np.float32)
    cx = np.zeros((total_num_envs, agent.rnn_hidden_size), np.float32)

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/env_interaction_time"):
            for _ in range(cfg.algo.rollout_steps):
                policy_step += total_num_envs

                obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
                prev_hx, prev_cx = hx, cx
                out, real_actions, hx, cx, key = policy_step_fn(
                    act_params, obs_host, jnp.asarray(prev_actions), jnp.asarray(prev_hx), jnp.asarray(prev_cx), key
                )
                real_actions_np = np.asarray(real_actions)
                if is_continuous:
                    env_actions = real_actions_np.reshape(envs.action_space.shape)
                else:
                    env_actions = real_actions_np.reshape(
                        (total_num_envs, -1) if is_multidiscrete else (total_num_envs,)
                    )

                obs, rewards, terminated, truncated, info = envs.step(env_actions)
                dones = np.logical_or(terminated, truncated).reshape(total_num_envs, 1).astype(np.float32)
                rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, 1)

                # truncation bootstrap with the *post-step* recurrent states
                final_obs_arr = info.get("final_observation", info.get("final_obs"))
                truncated_envs = np.nonzero(truncated)[0]
                if final_obs_arr is not None and len(truncated_envs) > 0:
                    real_next_obs = {
                        k: np.stack(
                            [np.asarray(final_obs_arr[i][k], dtype=np.float32) for i in truncated_envs]
                        )
                        for k in obs_keys
                    }
                    actions_np = np.asarray(out["actions"], np.float32)
                    vals = np.asarray(
                        get_values(
                            act_params,
                            real_next_obs,
                            jnp.asarray(actions_np[truncated_envs]),
                            jnp.asarray(np.asarray(hx)[truncated_envs]),
                            jnp.asarray(np.asarray(cx)[truncated_envs]),
                        )
                    ).reshape(len(truncated_envs))
                    rewards[truncated_envs] += cfg.algo.gamma * vals.reshape(-1, 1)

                step_data["dones"] = dones[np.newaxis]
                step_data["values"] = np.asarray(out["values"], np.float32)[np.newaxis]
                step_data["actions"] = np.asarray(out["actions"], np.float32)[np.newaxis]
                step_data["logprobs"] = np.asarray(out["logprob"], np.float32)[np.newaxis]
                step_data["rewards"] = rewards[np.newaxis]
                step_data["prev_actions"] = prev_actions[np.newaxis]
                step_data["prev_hx"] = np.asarray(prev_hx, np.float32)[np.newaxis]
                step_data["prev_cx"] = np.asarray(prev_cx, np.float32)[np.newaxis]
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                prev_actions = (1 - dones) * np.asarray(out["actions"], np.float32)
                next_obs = obs
                for k in obs_keys:
                    step_data[k] = np.asarray(obs[k])[np.newaxis]

                # reset recurrent state on done (reference ppo_recurrent.py:368-371)
                if cfg.algo.reset_recurrent_state_on_done:
                    hx = (1 - dones) * np.asarray(hx)
                    cx = (1 - dones) * np.asarray(cx)
                else:
                    hx, cx = np.asarray(hx), np.asarray(cx)

                ep_info = info.get("final_info", info)
                if "episode" in ep_info:
                    ep = ep_info["episode"]
                    mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
                    rews, lens = ep["r"][mask], ep["l"][mask]
                    if len(rews) > 0:
                        telemetry.observe_episodes(rews, lens)
                        if aggregator and not aggregator.disabled:
                            aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                            aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        # bootstrap + GAE on host arrays
        obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
        next_values = np.asarray(
            get_values(
                act_params, obs_host, jnp.asarray(prev_actions), jnp.asarray(hx), jnp.asarray(cx)
            )
        )
        local_data = {k: np.asarray(rb[k], dtype=np.float32) for k in rb.buffer.keys()}
        returns, advantages = jax.device_get(
            gae(
                jnp.asarray(local_data["rewards"]),
                jnp.asarray(local_data["values"]),
                jnp.asarray(local_data["dones"]),
                jnp.asarray(next_values),
                cfg.algo.rollout_steps,
                cfg.algo.gamma,
                cfg.algo.gae_lambda,
            )
        )
        local_data["returns"] = np.asarray(returns, np.float32)
        local_data["advantages"] = np.asarray(advantages, np.float32)

        # split into episodes → fixed-length sequences → padded [sl, N, ...] block
        # (reference ppo_recurrent.py:405-445, numpy instead of torch pad_sequence)
        sequences: Dict[str, List[np.ndarray]] = {k: [] for k in local_data}
        lengths: List[int] = []
        for env_id in range(total_num_envs):
            ep_ends = local_data["dones"][:, env_id, 0].nonzero()[0].tolist()
            ep_ends.append(cfg.algo.rollout_steps - 1)
            start = 0
            for stop in ep_ends:
                if stop + 1 <= start:
                    continue
                for k in local_data:
                    ep = local_data[k][start : stop + 1, env_id]
                    for s0 in range(0, ep.shape[0], sl):
                        sequences[k].append(ep[s0 : s0 + sl])
                ep_len = stop + 1 - start
                lengths.extend(
                    [min(sl, ep_len - s0) for s0 in range(0, ep_len, sl)]
                )
                start = stop + 1
        num_seq = len(lengths)
        n_pad = _next_pow2(max(num_seq, num_batches))
        seqs: Dict[str, np.ndarray] = {}
        for k, chunks in sequences.items():
            if k in ("dones", "rewards"):
                continue  # folded into returns/advantages; not read by the loss
            arr = np.zeros((sl, n_pad, *chunks[0].shape[1:]), np.float32)
            for j, c in enumerate(chunks):
                arr[: c.shape[0], j] = c
            # only the sequence-start recurrent state seeds the unroll
            seqs[k] = arr[:1] if k in ("prev_hx", "prev_cx") else arr
        mask = np.zeros((sl, n_pad, 1), np.float32)
        for j, ln in enumerate(lengths):
            mask[:ln, j] = 1.0
        seqs["mask"] = mask

        with timer("Time/train_time"):
            if world_size > 1:
                seqs = jax.device_put(seqs, fabric.sharding(None, "data"))
            key, train_key = jax.random.split(key)
            # one-shot injected learning pathology (resilience.fault=lr_spike):
            # identity unless the fault armed this iteration
            params = apply_armed_learn_fault(params)
            params, opt_state, mean_losses, learn = train_phase(
                params, opt_state, seqs, np.asarray(train_key), clip_coef, ent_coef
            )
            telemetry.observe_train(1, mean_losses)
            telemetry.observe_learn(learn)
            if telemetry.wants_program("train_phase"):
                telemetry.register_program(
                    "train_phase",
                    train_phase,
                    (params, opt_state, seqs, np.asarray(train_key), clip_coef, ent_coef),
                    units=1,
                )
            if aggregator and not aggregator.disabled:
                losses_np = np.asarray(mean_losses)
                aggregator.update("Loss/policy_loss", losses_np[0])
                aggregator.update("Loss/value_loss", losses_np[1])
                aggregator.update("Loss/entropy_loss", losses_np[2])
            act_params = act.view(params)

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (policy_step - last_log) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (policy_step - last_log)
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step

        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                iter_num, initial=initial_clip_coef, final=0.0, max_decay_steps=total_iters, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                iter_num, initial=initial_ent_coef, final=0.0, max_decay_steps=total_iters, power=1.0
            )

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "optimizer": opt_state,
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size if cfg.algo.get("per_rank_batch_size") else 0,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            with timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(agent, params, fabric, cfg, log_dir)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
