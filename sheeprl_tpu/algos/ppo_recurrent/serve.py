"""Recurrent-PPO serving extractor: the GRU/LSTM case of the O(1) session
state argument (howto/serving.md). The per-session carry is (prev one-hot
action, hx, cx, key) — a few KB per slot, device-resident, updated in place by
the donated slot-table step program; the host never sees it."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import make_dists
from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent
from sheeprl_tpu.serve.policy import ServePolicy, space_obs_spec
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_serve_policy


@register_serve_policy(algorithms=["ppo_recurrent"])
def get_serve_policy(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> ServePolicy:
    env = make_env(cfg, cfg.seed, 0, None, "serve-probe")()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    action_shape = tuple(int(s) for s in action_space.shape)
    env.close()

    agent, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, observation_space, jax.random.PRNGKey(cfg.seed)
    )
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state["agent"])

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    greedy = bool((cfg.get("serve") or {}).get("greedy", True))
    act_dim_total = int(np.sum(actions_dim))
    hidden = int(agent.rnn_hidden_size)
    splits = np.cumsum(actions_dim)[:-1].tolist()

    def init_slot(params, key):
        return {
            "prev_action": jnp.zeros((act_dim_total,), jnp.float32),
            "hx": jnp.zeros((hidden,), jnp.float32),
            "cx": jnp.zeros((hidden,), jnp.float32),
            "key": key,
        }

    def step_slot(params, carry, obs):
        key, step_key = jax.random.split(carry["key"])
        norm: Dict[str, jax.Array] = {}
        for k in obs_keys:
            v = obs[k].astype(jnp.float32)
            if k in cnn_keys:
                norm[k] = v.reshape(-1, *v.shape[-2:]) / 255.0 - 0.5
            else:
                norm[k] = v.reshape(-1)
        feat = agent.feature_extractor.apply({"params": params["feature_extractor"]}, norm)
        x = jnp.concatenate([feat, carry["prev_action"]], axis=-1)[None]  # [1, F+A]
        (cx, hx), out = agent.rnn.apply(
            {"params": params["rnn"]}, (carry["cx"][None], carry["hx"][None]), x
        )
        rnn_out = out[0]
        pre_dist = agent.actor.apply({"params": params["actor"]}, rnn_out)
        dists = make_dists(pre_dist, is_continuous)
        if is_continuous:
            dist = dists[0]
            act = dist.mode if greedy else dist.sample(step_key)
            stored = act
            env_action = act.reshape(action_shape).astype(jnp.float32)
        else:
            keys = jax.random.split(step_key, len(dists))
            blocks = [
                d.mode if greedy else d.sample(keys[i]) for i, d in enumerate(dists)
            ]
            stored = jnp.concatenate(blocks, axis=-1)
            env_action = jnp.stack([b.argmax(axis=-1) for b in blocks], axis=-1).reshape(
                action_shape
            ).astype(jnp.int32)
        return env_action, {
            "prev_action": stored.reshape(act_dim_total).astype(jnp.float32),
            "hx": hx[0],
            "cx": cx[0],
            "key": key,
        }

    return ServePolicy(
        algo=str(cfg.algo.name),
        params=params,
        init_slot=init_slot,
        step_slot=step_slot,
        obs_spec=space_obs_spec(observation_space, obs_keys),
        action_shape=action_shape,
        action_dtype=np.float32 if is_continuous else np.int32,
        meta={"family": "ppo_recurrent", "greedy": greedy, "recurrent": True},
    )
