"""Recurrent PPO agent, Flax/JAX-native.

Capability parity with the reference (sheeprl/algos/ppo_recurrent/agent.py:
RecurrentModel:18, RecurrentPPOAgent:86, RecurrentPPOPlayer:266): multi-key CNN+MLP
encoder → optional pre-MLP → LSTM → optional post-MLP → actor heads + critic.

The sequence unroll is a pure ``lax.scan`` over time with a mask-gated carry
(replacing torch's pack_padded_sequence machinery); the same step function serves
the per-env act path (T=1) and full-sequence training."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import CNNEncoder, MLPEncoder
from sheeprl_tpu.models.models import MLP, MultiEncoder


class RNNCore(nn.Module):
    """Optional pre-MLP → LSTM cell → optional post-MLP, one timestep."""

    lstm_hidden_size: int
    pre_mlp: Dict[str, Any]
    post_mlp: Dict[str, Any]
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, carry: Tuple[jax.Array, jax.Array], x: jax.Array):
        if self.pre_mlp.get("apply", False):
            x = MLP(
                hidden_sizes=(self.pre_mlp["dense_units"],),
                activation=self.pre_mlp["activation"],
                layer_norm=self.pre_mlp["layer_norm"],
                dtype=self.dtype,
            )(x)
        carry, out = nn.OptimizedLSTMCell(self.lstm_hidden_size, dtype=self.dtype)(carry, x)
        if self.post_mlp.get("apply", False):
            out = MLP(
                hidden_sizes=(self.post_mlp["dense_units"],),
                activation=self.post_mlp["activation"],
                layer_norm=self.post_mlp["layer_norm"],
                dtype=self.dtype,
            )(out)
        return carry, out


class ActorHeads(nn.Module):
    actions_dim: Sequence[int]
    is_continuous: bool
    dense_units: int
    mlp_layers: int
    dense_act: Any
    layer_norm: bool
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> List[jax.Array]:
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(x)
        if self.is_continuous:
            return [nn.Dense(int(np.sum(self.actions_dim)) * 2, dtype=self.dtype)(x)]
        return [nn.Dense(dim, dtype=self.dtype)(x) for dim in self.actions_dim]


@dataclass
class RecurrentPPOAgent:
    """Module container + pure scan programs; params layout:
    {"feature_extractor", "rnn", "actor", "critic"}."""

    feature_extractor: MultiEncoder
    rnn: RNNCore
    actor: ActorHeads
    critic: MLP
    actions_dim: Sequence[int]
    is_continuous: bool
    rnn_hidden_size: int

    def initial_states(self, num_envs: int) -> Tuple[jax.Array, jax.Array]:
        return (
            jnp.zeros((num_envs, self.rnn_hidden_size), jnp.float32),
            jnp.zeros((num_envs, self.rnn_hidden_size), jnp.float32),
        )

    def rnn_scan(
        self,
        params: Dict,
        embedded: jax.Array,  # [T, B, F+A] (features ++ prev_actions)
        hx: jax.Array,  # [B, H]
        cx: jax.Array,  # [B, H]
        mask: Optional[jax.Array] = None,  # [T, B, 1] — padded steps keep the carry
    ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
        def step(carry, inp):
            x, m = inp
            new_carry, out = self.rnn.apply({"params": params["rnn"]}, carry, x)
            if m is not None:
                new_carry = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(m, n, o), new_carry, carry
                )
            return new_carry, out

        if mask is None:
            mask_seq = jnp.ones((*embedded.shape[:2], 1), bool)
        else:
            mask_seq = mask
        (cx, hx), outs = jax.lax.scan(step, (cx, hx), (embedded, mask_seq))
        return outs, (hx, cx)

    def forward(
        self,
        params: Dict,
        obs: Dict[str, jax.Array],  # [T, B, ...]
        prev_actions: jax.Array,  # [T, B, A]
        hx: jax.Array,
        cx: jax.Array,
        mask: Optional[jax.Array] = None,
    ) -> Tuple[List[jax.Array], jax.Array, Tuple[jax.Array, jax.Array]]:
        """Full forward over a (possibly padded) sequence: returns
        (actor pre-dist outs, values, new (hx, cx))."""
        feat = self.feature_extractor.apply({"params": params["feature_extractor"]}, obs)
        rnn_out, states = self.rnn_scan(
            params, jnp.concatenate([feat, prev_actions], axis=-1), hx, cx, mask
        )
        pre_dist = self.actor.apply({"params": params["actor"]}, rnn_out)
        values = self.critic.apply({"params": params["critic"]}, rnn_out)
        return pre_dist, values, states


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
) -> Tuple[RecurrentPPOAgent, Dict]:
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    enc_cfg = cfg.algo.encoder
    rnn_cfg = cfg.algo.rnn
    dtype = fabric.compute_dtype

    cnn_encoder = (
        CNNEncoder(
            keys=cnn_keys,
            features_dim=enc_cfg.cnn_features_dim,
            screen_size=cfg.env.screen_size,
            dtype=dtype,
        )
        if len(cnn_keys) > 0
        else None
    )
    mlp_encoder = (
        MLPEncoder(
            keys=mlp_keys,
            features_dim=enc_cfg.mlp_features_dim,
            dense_units=enc_cfg.dense_units,
            mlp_layers=enc_cfg.mlp_layers,
            dense_act=enc_cfg.dense_act,
            layer_norm=enc_cfg.layer_norm,
            dtype=dtype,
        )
        if len(mlp_keys) > 0
        else None
    )
    feature_extractor = MultiEncoder(cnn_encoder, mlp_encoder)
    rnn = RNNCore(
        lstm_hidden_size=rnn_cfg.lstm.hidden_size,
        pre_mlp=dict(rnn_cfg.pre_rnn_mlp),
        post_mlp=dict(rnn_cfg.post_rnn_mlp),
        dtype=dtype,
    )
    actor = ActorHeads(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        dense_act=cfg.algo.actor.dense_act,
        layer_norm=cfg.algo.actor.layer_norm,
        dtype=dtype,
    )
    critic = MLP(
        hidden_sizes=(cfg.algo.critic.dense_units,) * cfg.algo.critic.mlp_layers,
        output_dim=1,
        activation=cfg.algo.critic.dense_act,
        layer_norm=cfg.algo.critic.layer_norm,
        dtype=dtype,
    )

    agent = RecurrentPPOAgent(
        feature_extractor=feature_extractor,
        rnn=rnn,
        actor=actor,
        critic=critic,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        rnn_hidden_size=rnn_cfg.lstm.hidden_size,
    )

    keys = jax.random.split(key, 4)
    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    fe_vars = feature_extractor.init(keys[0], dummy_obs)
    feat = feature_extractor.apply(fe_vars, dummy_obs)
    act_dim = int(np.sum(actions_dim))
    h = jnp.zeros((1, rnn_cfg.lstm.hidden_size), jnp.float32)
    rnn_in = jnp.concatenate([feat, jnp.zeros((1, act_dim), jnp.float32)], axis=-1)
    params = {
        "feature_extractor": fe_vars["params"],
        "rnn": rnn.init(keys[1], (h, h), rnn_in)["params"],
        "actor": actor.init(keys[2], h)["params"],
        "critic": critic.init(keys[3], h)["params"],
    }
    return agent, params
