"""Recurrent-PPO helpers (reference: sheeprl/algos/ppo_recurrent/utils.py)."""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import policy_output
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, normalize_obs  # noqa: F401

MODELS_TO_REGISTER = {"agent"}


def test(agent, params, fabric, cfg: Dict[str, Any], log_dir: str) -> None:
    """Greedy single-env rollout carrying the LSTM state across steps."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, None, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    key = jax.random.PRNGKey(cfg.seed)
    act_dim = int(np.sum(agent.actions_dim))
    prev_actions = jnp.zeros((1, act_dim), jnp.float32)
    hx, cx = agent.initial_states(1)
    cnn_keys = cfg.algo.cnn_keys.encoder
    obs_keys = cfg.algo.cnn_keys.encoder + cfg.algo.mlp_keys.encoder
    while not done:
        host = {}
        for k in obs_keys:
            v = np.asarray(obs[k], dtype=np.float32)
            host[k] = v.reshape(1, -1, *v.shape[-2:]) if k in cnn_keys else v.reshape(1, -1)
        norm = normalize_obs(host, cnn_keys, obs_keys)
        norm = {k: jnp.asarray(v)[None] for k, v in norm.items()}
        pre_dist, values, (hx, cx) = agent.forward(params, norm, prev_actions[None], hx, cx)
        key, sub = jax.random.split(key)
        out = policy_output(
            [p[0] for p in pre_dist], values[0], sub, agent.actions_dim, agent.is_continuous, greedy=True
        )
        actions = np.asarray(out["actions"])
        prev_actions = jnp.asarray(actions)
        if agent.is_continuous:
            real_actions = actions.reshape(env.action_space.shape)
        else:
            splits = np.cumsum(agent.actions_dim)[:-1]
            real_actions = np.stack(
                [b.argmax(-1) for b in np.split(actions[0], splits, axis=-1)], axis=-1
            ).reshape(env.action_space.shape)
        obs, reward, terminated, truncated, _ = env.step(real_actions)
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(np.asarray(reward))
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
