"""Dreamer-V2 world-model loss (reference sheeprl/algos/dreamer_v2/loss.py:9-91):
KL balancing with alpha (0.8 toward training the prior) and free nats applied to the
(optionally averaged) KL."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def categorical_kl(post_logits: jax.Array, prior_logits: jax.Array, discrete: int) -> jax.Array:
    post = post_logits.reshape(*post_logits.shape[:-1], -1, discrete)
    prior = prior_logits.reshape(*prior_logits.shape[:-1], -1, discrete)
    post_lp = jax.nn.log_softmax(post, axis=-1)
    prior_lp = jax.nn.log_softmax(prior, axis=-1)
    return jnp.sum(jnp.exp(post_lp) * (post_lp - prior_lp), axis=-1).sum(axis=-1)


def reconstruction_loss(
    observation_log_probs: Dict[str, jax.Array],
    reward_log_prob: jax.Array,
    priors_logits: jax.Array,
    posteriors_logits: jax.Array,
    discrete_size: int,
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 0.0,
    kl_free_avg: bool = True,
    kl_regularizer: float = 1.0,
    continue_log_prob: Optional[jax.Array] = None,
    discount_scale_factor: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (loss, kl, state_loss, reward_loss, observation_loss, continue_loss)."""
    observation_loss = -sum(lp.mean() for lp in observation_log_probs.values())
    reward_loss = -reward_log_prob.mean()
    lhs = kl = categorical_kl(jax.lax.stop_gradient(posteriors_logits), priors_logits, discrete_size)
    rhs = categorical_kl(posteriors_logits, jax.lax.stop_gradient(priors_logits), discrete_size)
    if kl_free_avg:
        loss_lhs = jnp.maximum(lhs.mean(), kl_free_nats)
        loss_rhs = jnp.maximum(rhs.mean(), kl_free_nats)
    else:
        loss_lhs = jnp.maximum(lhs, kl_free_nats).mean()
        loss_rhs = jnp.maximum(rhs, kl_free_nats).mean()
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs
    if continue_log_prob is not None:
        continue_loss = discount_scale_factor * -continue_log_prob.mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    loss = kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss
    return loss, kl.mean(), kl_loss, reward_loss, observation_loss, continue_loss
