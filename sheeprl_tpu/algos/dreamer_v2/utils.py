"""Dreamer-V2 support (reference: sheeprl/algos/dreamer_v2/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_HALF_LOG_2PI = 0.5 * float(np.log(2.0 * np.pi))


def normal1_logprob(pred: jax.Array, target: jax.Array, event_dims: int) -> jax.Array:
    """log N(target | pred, 1) summed over the rightmost ``event_dims`` dims."""
    lp = -0.5 * jnp.square(target - pred) - _HALF_LOG_2PI
    return lp.sum(axis=tuple(range(-event_dims, 0)))


def bernoulli_logprob(logits: jax.Array, target: jax.Array, event_dims: int) -> jax.Array:
    """Soft-target Bernoulli log-prob (torch's BCE-with-logits form): the continue
    targets are (1 - terminated) * gamma, not hard 0/1."""
    lp = target * jax.nn.log_sigmoid(logits) + (1.0 - target) * jax.nn.log_sigmoid(-logits)
    return lp.sum(axis=tuple(range(-event_dims, 0)))


AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/post_entropy",
    "State/prior_entropy",
    "State/kl",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    bootstrap: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """DV2 lambda-return recursion with explicit bootstrap (reference
    dreamer_v2/utils.py:85-102), as a reversed lax.scan.

    Accumulates in float32 regardless of compute precision (see the shared
    compute_lambda_values note in utils/utils.py): mixed bf16/fp32 inputs would
    otherwise break the scan carry-type invariant."""
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    continues = continues.astype(jnp.float32)
    bootstrap = bootstrap.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], bootstrap], axis=0)
    inputs = rewards + continues * next_values * (1 - lmbda)

    def step(agg, inp):
        inp_t, cont_t = inp
        agg = inp_t + cont_t * lmbda * agg
        return agg, agg

    _, lv_rev = jax.lax.scan(step, bootstrap[0], (inputs[::-1], continues[::-1]))
    return lv_rev[::-1]


def prepare_obs(
    fabric, obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), mlp_keys: Sequence[str] = (), num_envs: int = 1
) -> Dict[str, np.ndarray]:
    # host arrays: the act program's placement follows the player params (see the
    # dreamer_v3 prepare_obs note on avoiding a per-frame accelerator round-trip)
    out: Dict[str, np.ndarray] = {}
    for k in cnn_keys:
        v = np.asarray(obs[k], dtype=np.float32)
        out[k] = v.reshape(num_envs, -1, *v.shape[-2:]) / 255.0 - 0.5
    for k in mlp_keys:
        v = np.asarray(obs[k], dtype=np.float32)
        out[k] = v.reshape(num_envs, -1)
    return out


def test(player, params, fabric, cfg: Dict[str, Any], log_dir: str, test_name: str = "", greedy: bool = True):
    """Play one episode with the frozen params (reference utils.py test)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test" + (f"_{test_name}" if test_name else ""))()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    player.num_envs = 1
    player.init_states(params)
    key = jax.random.PRNGKey(cfg.seed)
    actions_dim = player.agent.actions_dim
    while not done:
        jobs = prepare_obs(
            fabric, obs, cnn_keys=cfg.algo.cnn_keys.encoder, mlp_keys=cfg.algo.mlp_keys.encoder, num_envs=1
        )
        actions, key = player.get_actions(params, jobs, key, greedy=greedy)
        actions = np.asarray(actions)
        if player.agent.is_continuous:
            real_actions = actions[0]
        else:
            splits = np.cumsum(actions_dim)[:-1]
            real_actions = np.stack([b.argmax(-1) for b in np.split(actions[0], splits, axis=-1)], axis=-1)
        obs, reward, terminated, truncated, _ = env.step(real_actions.reshape(env.action_space.shape))
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(np.asarray(reward))
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
