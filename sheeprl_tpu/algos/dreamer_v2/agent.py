"""Dreamer-V2 agent, Flax/JAX-native.

Capability parity with the reference agent (sheeprl/algos/dreamer_v2/agent.py:
CNNEncoder:31, MLPEncoder:84, CNNDecoder:129, MLPDecoder:191, RecurrentModel:240,
RSSM:287, PlayerDV2:735, Actor:416, build_agent:884) in the same pure-scan style as
the Dreamer-V3 module: discrete-latent RSSM without unimix, zero initial states,
ELU activations, optional layer norm, TruncatedNormal continuous policy with
exploration-noise support."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import LayerNormGRUCell, resolve_activation
from sheeprl_tpu.ops.conv import FastConv2x
from sheeprl_tpu.ops.deconv import FusedConvTransposeS2Valid
from sheeprl_tpu.utils.distribution import TruncatedNormal


class DenseStack(nn.Module):
    """[Dense → (LayerNorm) → act] × n — the Dreamer-V1/V2 MLP block (bias kept when
    no norm; reference MLP usage with norm_layer optional)."""

    units: int
    n_layers: int
    activation: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = resolve_activation(self.activation)
        x = x.astype(self.dtype)
        for _ in range(self.n_layers):
            x = nn.Dense(self.units, use_bias=not self.layer_norm, dtype=self.dtype)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=1e-3, dtype=self.dtype)(x)
            x = act(x)
        return x


class MLPHead(nn.Module):
    units: int
    n_layers: int
    output_dim: int
    activation: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.units, self.n_layers, self.activation, self.layer_norm, self.dtype)(x)
        return nn.Dense(self.output_dim, dtype=self.dtype)(x)


class CNNEncoder(nn.Module):
    """4 k4-s2 VALID convs, channels [1,2,4,8]×multiplier (reference agent.py:31-81);
    64×64 → 2×2, flattened."""

    keys: Sequence[str]
    channels_multiplier: int
    activation: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        act = resolve_activation(self.activation)
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        lead = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:])
        x = jnp.moveaxis(x, -3, -1).astype(self.dtype)
        for i, mult in enumerate((1, 2, 4, 8)):
            # CPU fast-gradient stride-2 conv (ops/conv.py; TPU keeps the native
            # lowering); explicit name keeps nn.Conv's parameter tree
            x = FastConv2x(
                features=mult * self.channels_multiplier,
                kernel_size=4,
                use_bias=not self.layer_norm,
                dtype=self.dtype,
                name=f"Conv_{i}",
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=1e-3, dtype=self.dtype)(x)
            x = act(x)
        return x.reshape(*lead, -1)


class MLPEncoder(nn.Module):
    keys: Sequence[str]
    mlp_layers: int = 4
    dense_units: int = 400
    activation: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return DenseStack(self.dense_units, self.mlp_layers, self.activation, self.layer_norm, self.dtype)(x)


class Encoder(nn.Module):
    cnn_encoder: Optional[CNNEncoder]
    mlp_encoder: Optional[MLPEncoder]

    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs))
        return jnp.concatenate(outs, axis=-1)


class CNNDecoder(nn.Module):
    """latent → Dense(enc_out) → 1×1 spatial → deconvs k5,k5,k6,k6 s2 VALID → 64×64
    (reference agent.py:129-188)."""

    keys: Sequence[str]
    output_channels: Sequence[int]
    channels_multiplier: int
    cnn_encoder_output_dim: int
    activation: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        act = resolve_activation(self.activation)
        lead = latent.shape[:-1]
        x = nn.Dense(self.cnn_encoder_output_dim, dtype=self.dtype)(latent)
        x = x.reshape(-1, 1, 1, self.cnn_encoder_output_dim)
        specs = [
            (4 * self.channels_multiplier, 5),
            (2 * self.channels_multiplier, 5),
            (1 * self.channels_multiplier, 6),
        ]
        # FusedConvTransposeS2Valid == nn.ConvTranspose(k, s=2, VALID) exactly
        # (ops/deconv.py; parity-tested), ~3x faster under XLA:CPU's lowering;
        # explicit names keep the nn.ConvTranspose param tree (checkpoints intact).
        for i, (ch, k) in enumerate(specs):
            x = FusedConvTransposeS2Valid(
                ch,
                kernel_size=k,
                use_bias=not self.layer_norm,
                dtype=self.dtype,
                name=f"ConvTranspose_{i}",
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=1e-3, dtype=self.dtype)(x)
            x = act(x)
        x = FusedConvTransposeS2Valid(
            sum(self.output_channels),
            kernel_size=6,
            dtype=self.dtype,
            name=f"ConvTranspose_{len(specs)}",
        )(x)
        x = jnp.moveaxis(x, -1, -3)
        x = x.reshape(*lead, *x.shape[-3:])
        splits = np.cumsum(self.output_channels)[:-1].tolist()
        return {k: v for k, v in zip(self.keys, jnp.split(x, splits, axis=-3))}


class MLPDecoder(nn.Module):
    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 400
    activation: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        x = DenseStack(self.dense_units, self.mlp_layers, self.activation, self.layer_norm, self.dtype)(latent)
        return {k: nn.Dense(dim, dtype=self.dtype)(x) for k, dim in zip(self.keys, self.output_dims)}


class Decoder(nn.Module):
    cnn_decoder: Optional[CNNDecoder]
    mlp_decoder: Optional[MLPDecoder]

    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latent))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent))
        return out


class RecurrentModel(nn.Module):
    """MLP projection + (layer-norm) GRU cell (reference agent.py:240-284)."""

    recurrent_state_size: int
    dense_units: int
    activation: Any = "elu"
    layer_norm: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, h: jax.Array) -> jax.Array:
        feat = DenseStack(self.dense_units, 1, self.activation, False, self.dtype)(x)
        return LayerNormGRUCell(
            hidden_size=self.recurrent_state_size,
            bias=True,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(h, feat)


class Actor(nn.Module):
    """Backbone + heads; continuous default is a tanh-mean TruncatedNormal
    (reference agent.py:416-574). Returns raw head outputs."""

    actions_dim: Sequence[int]
    is_continuous: bool
    dense_units: int = 400
    mlp_layers: int = 4
    activation: Any = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array) -> List[jax.Array]:
        x = DenseStack(self.dense_units, self.mlp_layers, self.activation, self.layer_norm, self.dtype)(state)
        if self.is_continuous:
            return [nn.Dense(int(np.sum(self.actions_dim)) * 2, dtype=self.dtype)(x)]
        return [nn.Dense(dim, dtype=self.dtype)(x) for dim in self.actions_dim]


def st_onehot_sample(logits: jax.Array, key: Optional[jax.Array], sample: bool = True) -> jax.Array:
    """Straight-through one-hot sample (or mode) over the last axis."""
    if sample:
        idx = jax.random.categorical(key, logits, axis=-1)
        onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype)
        probs = jax.nn.softmax(logits, axis=-1)
        return jax.lax.stop_gradient(onehot) + probs - jax.lax.stop_gradient(probs)
    idx = jnp.argmax(logits, axis=-1)
    return jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype)


def stochastic_state(logits: jax.Array, discrete: int, key: Optional[jax.Array] = None, sample: bool = True) -> jax.Array:
    """ST sample of the [..., S, D] categorical stack, flat in/out."""
    shaped = logits.reshape(*logits.shape[:-1], -1, discrete)
    out = st_onehot_sample(shaped, key, sample)
    return out.reshape(*out.shape[:-2], -1)


def actor_sample(
    agent: "DV2Agent", pre_dist: List[jax.Array], key: jax.Array, greedy: bool = False
) -> jax.Array:
    """Sample concatenated actions (reference Actor.forward:505-556)."""
    cfg = agent.actor_cfg
    if agent.is_continuous:
        mean, std_raw = jnp.split(pre_dist[0], 2, axis=-1)
        mean = jnp.tanh(mean)
        std = 2 * jax.nn.sigmoid((std_raw + cfg["init_std"]) / 2) + cfg["min_std"]
        dist = TruncatedNormal(mean, std, -1.0, 1.0)
        return dist.mode if greedy else dist.rsample(key)
    keys = jax.random.split(key, len(pre_dist))
    outs = []
    for i, logits in enumerate(pre_dist):
        if greedy:
            outs.append(jax.nn.one_hot(jnp.argmax(logits, axis=-1), logits.shape[-1], dtype=logits.dtype))
        else:
            outs.append(st_onehot_sample(logits, keys[i]))
    return jnp.concatenate(outs, axis=-1)


def actor_logprob_entropy(
    agent: "DV2Agent", pre_dist: List[jax.Array], actions: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """(log-prob [..., 1], entropy [...]); continuous TruncatedNormal reports zero
    entropy like the reference's NotImplementedError fallback (dreamer_v2.py:334)."""
    cfg = agent.actor_cfg
    if agent.is_continuous:
        mean, std_raw = jnp.split(pre_dist[0], 2, axis=-1)
        mean = jnp.tanh(mean)
        std = 2 * jax.nn.sigmoid((std_raw + cfg["init_std"]) / 2) + cfg["min_std"]
        dist = TruncatedNormal(mean, std, -1.0, 1.0)
        lp = dist.log_prob(actions).sum(axis=-1, keepdims=True)
        return lp, jnp.zeros(lp.shape[:-1], lp.dtype)
    splits = np.cumsum(agent.actions_dim)[:-1].tolist()
    blocks = jnp.split(actions, splits, axis=-1)
    lps, ents = [], []
    for logits, act in zip(pre_dist, blocks):
        lp_all = jax.nn.log_softmax(logits, axis=-1)
        lps.append(jnp.sum(lp_all * act, axis=-1))
        ents.append(-jnp.sum(jnp.exp(lp_all) * lp_all, axis=-1))
    return jnp.stack(lps, axis=-1).sum(axis=-1, keepdims=True), jnp.stack(ents, axis=-1).sum(axis=-1)


@dataclass
class DV2Agent:
    """Params layout: {"world_model": {"encoder", "recurrent_model",
    "representation_model", "transition_model", "observation_model", "reward_model",
    "continue_model"?}, "actor", "critic", "target_critic"}."""

    encoder: Encoder
    recurrent_model: RecurrentModel
    representation_model: MLPHead
    transition_model: MLPHead
    observation_model: Decoder
    reward_model: MLPHead
    continue_model: Optional[MLPHead]
    actor: Actor
    critic: MLPHead
    actions_dim: Sequence[int]
    is_continuous: bool
    stochastic_size: int
    discrete_size: int
    recurrent_state_size: int
    actor_cfg: Dict[str, Any] = field(default_factory=dict)

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size * self.discrete_size

    @property
    def latent_state_size(self) -> int:
        return self.stoch_state_size + self.recurrent_state_size

    def _representation(self, wm, h, embedded, key):
        logits = self.representation_model.apply(
            {"params": wm["representation_model"]}, jnp.concatenate([h, embedded], axis=-1)
        )
        return logits, stochastic_state(logits, self.discrete_size, key)

    def _transition(self, wm, h, key):
        logits = self.transition_model.apply({"params": wm["transition_model"]}, h)
        return logits, stochastic_state(logits, self.discrete_size, key)

    def _recurrent(self, wm, z, a, h):
        return self.recurrent_model.apply(
            {"params": wm["recurrent_model"]}, jnp.concatenate([z, a], axis=-1), h
        )

    def dynamic_scan(self, wm, embedded, actions, is_first, key):
        """Posterior/prior unroll; zeros initial states, is_first masks
        (reference RSSM.dynamic:333-368)."""
        T, B = embedded.shape[:2]
        keys = jax.random.split(key, T)

        def step(carry, inp):
            h, z = carry
            a, e, first, k = inp
            a = (1 - first) * a
            h = (1 - first) * h
            z = (1 - first) * z
            h = self._recurrent(wm, z, a, h)
            prior_logits, _ = self._transition(wm, h, jax.random.fold_in(k, 0))
            post_logits, z = self._representation(wm, h, e, k)
            return (h, z), (h, z, post_logits, prior_logits)

        init = (
            jnp.zeros((B, self.recurrent_state_size), embedded.dtype),
            jnp.zeros((B, self.stoch_state_size), embedded.dtype),
        )
        _, (hs, zs, post_logits, prior_logits) = jax.lax.scan(
            step, init, (actions, embedded, is_first, keys)
        )
        return hs, zs, post_logits, prior_logits

    def imagination_scan(self, wm, actor_params, z0, h0, key, horizon, act_dim):
        """DV2 imagination (reference dreamer_v2.py:218-266): action[0] is zero, the
        actor acts before each imagination step. Returns (latents [H+1, N, L],
        actions [H+1, N, A])."""
        latent0 = jnp.concatenate([z0, h0], axis=-1)

        def step(carry, k):
            z, h, latent = carry
            pre = self.actor.apply({"params": actor_params}, jax.lax.stop_gradient(latent))
            a = actor_sample(self, pre, jax.random.fold_in(k, 1))
            h = self._recurrent(wm, z, a, h)
            _, z = self._transition(wm, h, k)
            latent = jnp.concatenate([z, h], axis=-1)
            return (z, h, latent), (latent, a)

        keys = jax.random.split(key, horizon)
        _, (latents, actions) = jax.lax.scan(step, (z0, h0, latent0), keys)
        latents = jnp.concatenate([latent0[None], latents], axis=0)
        a0 = jnp.zeros((1, z0.shape[0], act_dim), latents.dtype)
        actions = jnp.concatenate([a0, actions], axis=0)
        return latents, actions


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV2Agent, Dict[str, Any]]:
    wm_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    dtype = fabric.compute_dtype

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)

    cnn_encoder = (
        CNNEncoder(
            keys=cnn_keys,
            channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
            activation=cfg.algo.cnn_act,
            layer_norm=wm_cfg.encoder.get("layer_norm", cfg.algo.layer_norm),
            dtype=dtype,
        )
        if cnn_keys
        else None
    )
    mlp_encoder = (
        MLPEncoder(
            keys=mlp_keys,
            mlp_layers=wm_cfg.encoder.mlp_layers,
            dense_units=wm_cfg.encoder.dense_units,
            activation=cfg.algo.dense_act,
            layer_norm=wm_cfg.encoder.get("layer_norm", cfg.algo.layer_norm),
            dtype=dtype,
        )
        if mlp_keys
        else None
    )
    encoder = Encoder(cnn_encoder, mlp_encoder)

    stochastic_size = wm_cfg.stochastic_size
    discrete_size = wm_cfg.get("discrete_size", 1)
    stoch_state_size = stochastic_size * discrete_size
    recurrent_state_size = wm_cfg.recurrent_model.recurrent_state_size
    latent_state_size = stoch_state_size + recurrent_state_size

    recurrent_model = RecurrentModel(
        recurrent_state_size=recurrent_state_size,
        dense_units=wm_cfg.recurrent_model.dense_units,
        activation=cfg.algo.dense_act,
        layer_norm=wm_cfg.recurrent_model.get("layer_norm", True),
        dtype=dtype,
    )
    representation_model = MLPHead(
        units=wm_cfg.representation_model.hidden_size,
        n_layers=1,
        output_dim=stoch_state_size,
        activation=wm_cfg.representation_model.dense_act,
        layer_norm=wm_cfg.representation_model.get("layer_norm", cfg.algo.layer_norm),
        dtype=dtype,
    )
    transition_model = MLPHead(
        units=wm_cfg.transition_model.hidden_size,
        n_layers=1,
        output_dim=stoch_state_size,
        activation=wm_cfg.transition_model.dense_act,
        layer_norm=wm_cfg.transition_model.get("layer_norm", cfg.algo.layer_norm),
        dtype=dtype,
    )
    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    keys = jax.random.split(key, 10)
    enc_vars = encoder.init(keys[0], dummy_obs)
    embedded = encoder.apply(enc_vars, dummy_obs)
    cnn_encoder_output_dim = (
        int(np.asarray(cnn_encoder.apply({"params": enc_vars["params"]["cnn_encoder"]}, dummy_obs)).shape[-1])
        if cnn_encoder is not None
        else 0
    )

    cnn_decoder = (
        CNNDecoder(
            keys=cnn_dec_keys,
            output_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_dec_keys],
            channels_multiplier=wm_cfg.observation_model.cnn_channels_multiplier,
            cnn_encoder_output_dim=cnn_encoder_output_dim,
            activation=cfg.algo.cnn_act,
            layer_norm=wm_cfg.observation_model.get("layer_norm", cfg.algo.layer_norm),
            dtype=dtype,
        )
        if cnn_dec_keys
        else None
    )
    mlp_decoder = (
        MLPDecoder(
            keys=mlp_dec_keys,
            output_dims=[obs_space[k].shape[0] for k in mlp_dec_keys],
            mlp_layers=wm_cfg.observation_model.mlp_layers,
            dense_units=wm_cfg.observation_model.dense_units,
            activation=cfg.algo.dense_act,
            layer_norm=wm_cfg.observation_model.get("layer_norm", cfg.algo.layer_norm),
            dtype=dtype,
        )
        if mlp_dec_keys
        else None
    )
    observation_model = Decoder(cnn_decoder, mlp_decoder)
    reward_model = MLPHead(
        units=wm_cfg.reward_model.dense_units,
        n_layers=wm_cfg.reward_model.mlp_layers,
        output_dim=1,
        activation=cfg.algo.dense_act,
        layer_norm=wm_cfg.reward_model.get("layer_norm", cfg.algo.layer_norm),
        dtype=dtype,
    )
    continue_model = (
        MLPHead(
            units=wm_cfg.discount_model.dense_units,
            n_layers=wm_cfg.discount_model.mlp_layers,
            output_dim=1,
            activation=cfg.algo.dense_act,
            layer_norm=wm_cfg.discount_model.get("layer_norm", cfg.algo.layer_norm),
            dtype=dtype,
        )
        if wm_cfg.use_continues
        else None
    )
    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=actor_cfg.dense_units,
        mlp_layers=actor_cfg.mlp_layers,
        activation=actor_cfg.dense_act,
        layer_norm=actor_cfg.get("layer_norm", cfg.algo.layer_norm),
        dtype=dtype,
    )
    critic = MLPHead(
        units=critic_cfg.dense_units,
        n_layers=critic_cfg.mlp_layers,
        output_dim=1,
        activation=critic_cfg.dense_act,
        layer_norm=critic_cfg.get("layer_norm", cfg.algo.layer_norm),
        dtype=dtype,
    )

    agent = DV2Agent(
        encoder=encoder,
        recurrent_model=recurrent_model,
        representation_model=representation_model,
        transition_model=transition_model,
        observation_model=observation_model,
        reward_model=reward_model,
        continue_model=continue_model,
        actor=actor,
        critic=critic,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        stochastic_size=stochastic_size,
        discrete_size=discrete_size,
        recurrent_state_size=recurrent_state_size,
        actor_cfg={
            "init_std": actor_cfg.init_std,
            "min_std": actor_cfg.min_std,
            "expl_amount": actor_cfg.get("expl_amount", 0.0),
            "expl_decay": actor_cfg.get("expl_decay", 0.0),
            "expl_min": actor_cfg.get("expl_min", 0.0),
        },
    )

    act_dim = int(np.sum(actions_dim))
    h = jnp.zeros((1, recurrent_state_size), jnp.float32)
    z = jnp.zeros((1, stoch_state_size), jnp.float32)
    latent = jnp.zeros((1, latent_state_size), jnp.float32)
    wm_params = {
        "encoder": enc_vars["params"],
        "recurrent_model": recurrent_model.init(
            keys[1], jnp.concatenate([z, jnp.zeros((1, act_dim), jnp.float32)], axis=-1), h
        )["params"],
        "representation_model": representation_model.init(
            keys[2], jnp.concatenate([h, embedded], axis=-1)
        )["params"],
        "transition_model": transition_model.init(keys[3], h)["params"],
        "observation_model": observation_model.init(keys[4], latent)["params"],
        "reward_model": reward_model.init(keys[5], latent)["params"],
    }
    if continue_model is not None:
        wm_params["continue_model"] = continue_model.init(keys[6], latent)["params"]
    critic_params = critic.init(keys[8], latent)["params"]
    params = {
        "world_model": wm_params,
        "actor": actor.init(keys[7], latent)["params"],
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    if getattr(fabric, "model_parallel", False):
        # data x model mesh: land every kernel in its rule-derived model-axis
        # shard (parallel/sharding.py); a 1-D mesh leaves this a no-op
        params = fabric.shard_params(params)
    return agent, params


class PlayerDV2:
    """Stateful env-interaction wrapper (reference PlayerDV2, agent.py:735-884)."""

    def __init__(self, agent: DV2Agent, num_envs: int, cnn_keys: Sequence[str], mlp_keys: Sequence[str]):
        self.agent = agent
        self.num_envs = num_envs
        self.cnn_keys = tuple(cnn_keys)
        self.mlp_keys = tuple(mlp_keys)
        self.actions: Optional[jax.Array] = None
        self.recurrent_state: Optional[jax.Array] = None
        self.stochastic_state: Optional[jax.Array] = None

        agent_ref = self.agent

        def _step(params, obs, a, h, z, key, greedy: bool, expl_amount):
            wm = params["world_model"]
            embedded = agent_ref.encoder.apply({"params": wm["encoder"]}, obs)
            h = agent_ref._recurrent(wm, z, a, h)
            # chain key advanced in-program (saves ~0.5 ms/step of host dispatch)
            key, k_repr, k_act, k_expl = jax.random.split(key, 4)
            _, z = agent_ref._representation(wm, h, embedded, k_repr)
            latent = jnp.concatenate([z, h], axis=-1)
            pre = agent_ref.actor.apply({"params": params["actor"]}, latent)
            actions = actor_sample(agent_ref, pre, k_act, greedy=greedy)
            # expl_amount is a traced scalar: 0 makes the noise a no-op, so the
            # anneal schedule never triggers a recompile
            actions = add_exploration_noise(agent_ref, actions, k_expl, expl_amount)
            return actions, h, z, key

        self._step = jax.jit(_step, static_argnames=("greedy",))

    def init_states(self, params: Dict = None, reset_envs: Optional[Sequence[int]] = None) -> None:
        act_dim = int(np.sum(self.agent.actions_dim))
        if reset_envs is None or len(reset_envs) == 0:
            self.actions = jnp.zeros((self.num_envs, act_dim), jnp.float32)
            self.recurrent_state = jnp.zeros((self.num_envs, self.agent.recurrent_state_size), jnp.float32)
            self.stochastic_state = jnp.zeros((self.num_envs, self.agent.stoch_state_size), jnp.float32)
        else:
            idx = np.asarray(reset_envs)
            self.actions = self.actions.at[idx].set(0.0)
            self.recurrent_state = self.recurrent_state.at[idx].set(0.0)
            self.stochastic_state = self.stochastic_state.at[idx].set(0.0)

    def get_actions(
        self, params: Dict, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False, expl_amount: float = 0.0
    ):
        """Returns ``(actions, key)`` — the advanced PRNG chain key."""
        actions, self.recurrent_state, self.stochastic_state, key = self._step(
            params, obs, self.actions, self.recurrent_state, self.stochastic_state, key, greedy,
            jnp.asarray(expl_amount, jnp.float32),
        )
        self.actions = actions
        return actions, key


def add_exploration_noise(agent: DV2Agent, actions: jax.Array, key: jax.Array, expl_amount: float) -> jax.Array:
    """Gaussian noise (clipped to [-1,1]) for continuous actions; epsilon-uniform
    resampling per discrete head (reference Actor.add_exploration_noise:558-574)."""
    if agent.is_continuous:
        noise = jax.random.normal(key, actions.shape, actions.dtype) * expl_amount
        return jnp.clip(actions + noise, -1.0, 1.0)
    splits = np.cumsum(agent.actions_dim)[:-1].tolist()
    blocks = jnp.split(actions, splits, axis=-1)
    outs = []
    for i, act in enumerate(blocks):
        k_sample, k_mask = jax.random.split(jax.random.fold_in(key, i))
        idx = jax.random.randint(k_sample, act.shape[:-1], 0, act.shape[-1])
        sample = jax.nn.one_hot(idx, act.shape[-1], dtype=act.dtype)
        mask = jax.random.uniform(k_mask, act.shape[:1]) < expl_amount
        outs.append(jnp.where(mask[..., None], sample, act))
    return jnp.concatenate(outs, axis=-1)
