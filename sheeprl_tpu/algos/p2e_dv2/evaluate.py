"""P2E-DV2 evaluation entrypoint (reference: sheeprl/algos/p2e_dv2/evaluate.py) —
evaluates the task actor."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax

from sheeprl_tpu.algos.dreamer_v2.agent import PlayerDV2
from sheeprl_tpu.algos.p2e_dv2.agent import build_agent, player_params
from sheeprl_tpu.algos.p2e_dv2.utils import test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["p2e_dv2_exploration", "p2e_dv2_finetuning"])
def evaluate(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logdir = cfg.get("log_dir", "logs/evaluation")
    env = make_env(cfg, cfg.seed, 0, logdir, "test")()
    observation_space = env.observation_space
    action_space = env.action_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    env.close()
    agent_state = state["agent"] if state else None
    if agent_state is not None and "actor_task" not in agent_state:
        # finetuning checkpoints are saved in the plain dreamer layout
        from sheeprl_tpu.algos.dreamer_v2.agent import build_agent as build_dv_agent

        agent, params = build_dv_agent(
            fabric, actions_dim, is_continuous, cfg, observation_space,
            jax.random.PRNGKey(cfg.seed), agent_state,
        )
        player = PlayerDV2(agent, 1, cfg.algo.cnn_keys.encoder, cfg.algo.mlp_keys.encoder)
        test(player, params, fabric, cfg, logdir, greedy=False)
        return
    agent, _, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, observation_space,
        jax.random.PRNGKey(cfg.seed), agent_state,
    )
    player = PlayerDV2(agent, 1, cfg.algo.cnn_keys.encoder, cfg.algo.mlp_keys.encoder)
    test(player, player_params(params, "task"), fabric, cfg, logdir, greedy=False)
