"""Plan2Explore on the Dreamer-V2 backbone — exploration phase (capability parity
with sheeprl/algos/p2e_dv2/p2e_dv2_exploration.py:58-860): DV2 world model +
disagreement ensembles; the exploration actor maximizes the ensemble-variance
intrinsic reward with DV2-style (REINFORCE/dynamics mixed) behaviour learning; the
task heads train alongside on the extrinsic reward."""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v2.agent import (
    DV2Agent,
    PlayerDV2,
    actor_logprob_entropy,
)
from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v2.utils import (
    bernoulli_logprob as _bernoulli_logprob,
    compute_lambda_values,
    normal1_logprob as _normal1_logprob,
)
from sheeprl_tpu.algos.p2e_dv1.p2e_dv1_exploration import build_txs
from sheeprl_tpu.algos.p2e_dv2.agent import build_agent, player_params
from sheeprl_tpu.analysis.programs import register_fused_program
from sheeprl_tpu.algos.p2e_dv2.utils import prepare_obs, test
from sheeprl_tpu.algos.p2e_dv3.agent import EnsembleHeads
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.envs.wrappers import RestartOnException
from sheeprl_tpu.utils.distribution import MSEDistribution
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.mfu import unit_avals
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, Ratio, foreach_gradient_step, save_configs


def make_train_phase(
    agent: DV2Agent, ensembles: EnsembleHeads, cfg, txs: Dict[str, Any], state_shardings=None
):
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    wm_cfg = cfg.algo.world_model
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    horizon = int(cfg.algo.horizon)
    ent_coef = float(cfg.algo.actor.ent_coef)
    objective_mix = float(cfg.algo.actor.objective_mix)
    discrete_size = agent.discrete_size
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    use_continues = bool(wm_cfg.use_continues)
    intrinsic_mult = float(cfg.algo.intrinsic_reward_multiplier)
    act_dim = int(np.sum(agent.actions_dim))

    # compile the Learn/* stats only when the telemetry learning plane is on
    learn_on = learn_stats.enabled(cfg)

    def world_loss_fn(wm_params, batch, key):
        batch_obs = {k: batch[k] / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: batch[k] for k in mlp_keys})
        is_first = batch["is_first"].at[0].set(jnp.ones_like(batch["is_first"][0]))
        actions = jnp.concatenate(
            [jnp.zeros_like(batch["actions"][:1]), batch["actions"][:-1]], axis=0
        )
        embedded = agent.encoder.apply({"params": wm_params["encoder"]}, batch_obs)
        hs, zs, post_logits, prior_logits = agent.dynamic_scan(
            wm_params, embedded, actions, is_first, key
        )
        latents = jnp.concatenate([zs, hs], axis=-1)
        recon = agent.observation_model.apply({"params": wm_params["observation_model"]}, latents)
        obs_lps = {
            k: _normal1_logprob(recon[k], batch_obs[k], len(recon[k].shape[2:]))
            for k in cnn_dec_keys + mlp_dec_keys
        }
        reward_pred = agent.reward_model.apply({"params": wm_params["reward_model"]}, latents)
        reward_lp = _normal1_logprob(reward_pred, batch["rewards"], 1)
        cont_lp = None
        if use_continues:
            cont_logits = agent.continue_model.apply({"params": wm_params["continue_model"]}, latents)
            cont_lp = _bernoulli_logprob(cont_logits, (1.0 - batch["terminated"]) * gamma, 1)
        loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
            obs_lps,
            reward_lp,
            prior_logits,
            post_logits,
            discrete_size,
            kl_balancing_alpha=wm_cfg.kl_balancing_alpha,
            kl_free_nats=wm_cfg.kl_free_nats,
            kl_free_avg=wm_cfg.kl_free_avg,
            kl_regularizer=wm_cfg.kl_regularizer,
            continue_log_prob=cont_lp,
            discount_scale_factor=wm_cfg.discount_scale_factor,
        )

        def _cat_entropy(logits):
            shaped = logits.reshape(*logits.shape[:-1], -1, discrete_size)
            lp = jax.nn.log_softmax(shaped, axis=-1)
            return -jnp.sum(jnp.exp(lp) * lp, axis=(-2, -1)).mean()

        metrics = {
            "Loss/world_model_loss": loss,
            "Loss/observation_loss": observation_loss,
            "Loss/reward_loss": reward_loss,
            "Loss/state_loss": state_loss,
            "Loss/continue_loss": continue_loss,
            "State/kl": kl,
            "State/post_entropy": _cat_entropy(jax.lax.stop_gradient(post_logits)),
            "State/prior_entropy": _cat_entropy(jax.lax.stop_gradient(prior_logits)),
        }
        return loss, (zs, hs, metrics)

    def ensemble_loss_fn(ens_params, zs, hs, actions):
        inp = jax.lax.stop_gradient(jnp.concatenate([zs, hs, actions], axis=-1))
        out = ensembles.apply({"params": ens_params}, inp)[:, :-1]
        target = jax.lax.stop_gradient(zs)[1:][None]
        lp = MSEDistribution(out, dims=1).log_prob(jnp.broadcast_to(target, out.shape))
        return -lp.mean(axis=tuple(range(1, lp.ndim))).sum()

    def _behaviour(actor_key_params, params, zs, hs, true_continue, reward_fn, critic_key, target_key, key):
        """Shared DV2-style behaviour learning for one (actor, critic) pair."""
        wm = params["world_model"]
        z0 = jax.lax.stop_gradient(zs).reshape(-1, agent.stoch_state_size)
        h0 = jax.lax.stop_gradient(hs).reshape(-1, agent.recurrent_state_size)
        latents, actions = agent.imagination_scan(wm, actor_key_params, z0, h0, key, horizon, act_dim)
        predicted_target_values = agent.critic.apply({"params": params[target_key]}, latents)
        reward = reward_fn(latents, actions, wm, params)
        if use_continues:
            cont_logits = agent.continue_model.apply({"params": wm["continue_model"]}, latents)
            continues = jax.nn.sigmoid(cont_logits)
            continues = jnp.concatenate([true_continue[None] * gamma, continues[1:]], axis=0)
        else:
            continues = jnp.ones_like(jax.lax.stop_gradient(reward)) * gamma
        lambda_values = compute_lambda_values(
            reward[:-1],
            predicted_target_values[:-1],
            continues[:-1],
            bootstrap=predicted_target_values[-1:],
            lmbda=lmbda,
        )
        discount = jax.lax.stop_gradient(
            jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-1]], axis=0), axis=0)
        )
        pre = agent.actor.apply({"params": actor_key_params}, jax.lax.stop_gradient(latents[:-2]))
        lp, ent = actor_logprob_entropy(agent, pre, jax.lax.stop_gradient(actions[1:-1]))
        dynamics = lambda_values[1:]
        advantage = jax.lax.stop_gradient(lambda_values[1:] - predicted_target_values[:-2])
        reinforce = lp * advantage
        objective = objective_mix * reinforce + (1 - objective_mix) * dynamics
        entropy = ent_coef * ent[..., None]
        policy_loss = -jnp.mean(discount[:-2] * (objective + entropy))
        return policy_loss, (latents, lambda_values, discount, reward)

    def exploration_reward(latents, actions, wm, params):
        ens_in = jax.lax.stop_gradient(jnp.concatenate([latents, actions], axis=-1))
        ens_out = ensembles.apply({"params": params["ensembles"]}, ens_in)
        return ens_out.var(axis=0).mean(axis=-1, keepdims=True) * intrinsic_mult

    def task_reward(latents, actions, wm, params):
        return agent.reward_model.apply({"params": wm["reward_model"]}, latents)

    def actor_expl_loss_fn(actor_params, params, zs, hs, true_continue, key):
        return _behaviour(
            actor_params, params, zs, hs, true_continue, exploration_reward,
            "critic_exploration", "target_critic_exploration", key,
        )

    def actor_task_loss_fn(actor_params, params, zs, hs, true_continue, key):
        return _behaviour(
            actor_params, params, zs, hs, true_continue, task_reward,
            "critic_task", "target_critic_task", key,
        )

    def critic_loss_fn(critic_params, latents, lambda_values, discount):
        pred = agent.critic.apply({"params": critic_params}, latents[:-1])
        lp = _normal1_logprob(pred, jax.lax.stop_gradient(lambda_values), 1)
        return -jnp.mean(discount[:-1, ..., 0] * lp)

    # donate_argnums: XLA reuses the train-state buffers in place instead of
    # copying them every gradient step (drivers always rebind to the returned
    # trees, so the invalidated inputs are never read again).
    # state_shardings (parallel/sharding.py build_state_shardings) pins the
    # state outputs' mesh placement so GSPMD cannot reshard them on output.
    jit_kwargs = {"out_shardings": tuple(state_shardings)} if state_shardings is not None else {}

    @partial(jax.jit, donate_argnums=(0, 1), **jit_kwargs)
    def train_step(params, opt_state, batch, cum, k):
        k_world, k_expl, k_task = jax.random.split(jnp.asarray(k), 3)

        do_copy = (cum % target_freq) == 0
        hard = lambda t, c: jnp.where(do_copy, c, t)
        params = {
            **params,
            "target_critic_task": jax.tree_util.tree_map(
                hard, params["target_critic_task"], params["critic_task"]
            ),
            "target_critic_exploration": jax.tree_util.tree_map(
                hard, params["target_critic_exploration"], params["critic_exploration"]
            ),
        }

        (w_loss, (zs, hs, w_metrics)), w_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(
            params["world_model"], batch, k_world
        )
        updates, new_wopt = txs["world_model"].update(
            w_grads, opt_state["world_model"], params["world_model"]
        )
        params = {**params, "world_model": optax.apply_updates(params["world_model"], updates)}
        opt_state = {**opt_state, "world_model": new_wopt}

        e_loss, e_grads = jax.value_and_grad(ensemble_loss_fn)(
            params["ensembles"], zs, hs, batch["actions"]
        )
        updates, new_eopt = txs["ensembles"].update(e_grads, opt_state["ensembles"], params["ensembles"])
        params = {**params, "ensembles": optax.apply_updates(params["ensembles"], updates)}
        opt_state = {**opt_state, "ensembles": new_eopt}

        true_continue = (1 - batch["terminated"]).reshape(-1, 1)
        metrics = dict(w_metrics)

        (pe_loss, (latents_e, lambda_e, discount_e, intr_reward)), ae_grads = jax.value_and_grad(
            actor_expl_loss_fn, has_aux=True
        )(params["actor_exploration"], params, zs, hs, true_continue, k_expl)
        updates, new_aeopt = txs["actor_exploration"].update(
            ae_grads, opt_state["actor_exploration"], params["actor_exploration"]
        )
        params = {**params, "actor_exploration": optax.apply_updates(params["actor_exploration"], updates)}
        opt_state = {**opt_state, "actor_exploration": new_aeopt}

        ce_loss, ce_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic_exploration"], jax.lax.stop_gradient(latents_e), lambda_e, discount_e
        )
        updates, new_ceopt = txs["critic_exploration"].update(
            ce_grads, opt_state["critic_exploration"], params["critic_exploration"]
        )
        params = {**params, "critic_exploration": optax.apply_updates(params["critic_exploration"], updates)}
        opt_state = {**opt_state, "critic_exploration": new_ceopt}

        (pt_loss, (latents_t, lambda_t, discount_t, _)), at_grads = jax.value_and_grad(
            actor_task_loss_fn, has_aux=True
        )(params["actor_task"], params, zs, hs, true_continue, k_task)
        updates, new_atopt = txs["actor_task"].update(
            at_grads, opt_state["actor_task"], params["actor_task"]
        )
        params = {**params, "actor_task": optax.apply_updates(params["actor_task"], updates)}
        opt_state = {**opt_state, "actor_task": new_atopt}

        ct_loss, ct_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic_task"], jax.lax.stop_gradient(latents_t), lambda_t, discount_t
        )
        updates, new_ctopt = txs["critic_task"].update(
            ct_grads, opt_state["critic_task"], params["critic_task"]
        )
        params = {**params, "critic_task": optax.apply_updates(params["critic_task"], updates)}
        opt_state = {**opt_state, "critic_task": new_ctopt}

        metrics["Loss/ensemble_loss"] = e_loss
        metrics["Loss/policy_loss_exploration"] = pe_loss
        metrics["Loss/value_loss_exploration"] = ce_loss
        metrics["Loss/policy_loss_task"] = pt_loss
        metrics["Loss/value_loss_task"] = ct_loss
        metrics["Rewards/intrinsic"] = intr_reward.mean()
        metrics["Values_exploration/lambda_values"] = lambda_e.mean()
        metrics["Grads/world_model"] = optax.global_norm(w_grads)
        metrics["Grads/ensemble"] = optax.global_norm(e_grads)
        metrics["Grads/actor_exploration"] = optax.global_norm(ae_grads)
        metrics["Grads/critic_exploration"] = optax.global_norm(ce_grads)
        metrics["Grads/actor_task"] = optax.global_norm(at_grads)
        metrics["Grads/critic_task"] = optax.global_norm(ct_grads)
        if learn_on:
            # training-health block, riding the metrics dict (Learn/ prefix —
            # utils/learn_stats.py; extracted by RunTelemetry.observe_learn)
            metrics.update(learn_stats.group_stats(
                "world_model", grads=w_grads, params=params["world_model"]))
            metrics.update(learn_stats.group_stats(
                "ensemble", grads=e_grads, params=params["ensembles"]))
            metrics.update(learn_stats.group_stats(
                "actor_exploration", grads=ae_grads, params=params["actor_exploration"]))
            metrics.update(learn_stats.group_stats(
                "actor_task", grads=at_grads, params=params["actor_task"]))
            metrics.update(learn_stats.group_stats(
                "critic_task", grads=ct_grads, params=params["critic_task"]))
            metrics.update(learn_stats.kl_stats(
                w_metrics["State/kl"],
                w_metrics["State/post_entropy"],
                w_metrics["State/prior_entropy"],
            ))
            metrics.update(learn_stats.value_stats(jax.lax.stop_gradient(lambda_e)))
            metrics["Learn/loss/world_model"] = w_loss
            metrics["Learn/loss/ensemble"] = e_loss
            metrics["Learn/loss/actor_exploration"] = pe_loss
            metrics["Learn/loss/actor_task"] = pt_loss
            metrics["Learn/loss/critic_task"] = ct_loss
            metrics.update(learn_stats.group_stats(
                "critic_exploration", grads=ce_grads, params=params["critic_exploration"]))
            metrics["Learn/loss/critic_exploration"] = ce_loss
        return params, opt_state, metrics

    def train_phase(params, opt_state, data, cum_steps, train_key):
        return foreach_gradient_step(train_step, (params, opt_state), data, train_key, cum_steps)

    # the compiled unit, exposed for FLOPs/MFU accounting (utils/mfu.py, obs/)
    train_phase.train_step = train_step
    return train_phase


@register_fused_program(
    "p2e_dv2.train_step",
    min_donated=2,
    doc="fused single-gradient-step P2E-DV2 world/ensemble/task+exploration heads update",
)
def _aot_train_step():
    """Tiny P2E-DV2 agent (incl. the disagreement ensembles) through the loop's
    own factory."""
    from sheeprl_tpu.analysis.programs import (
        tiny_dreamer_batch,
        tiny_dreamer_cfg,
        tiny_fabric,
        tiny_obs_space,
    )

    cfg = tiny_dreamer_cfg(
        "p2e_dv2_exploration",
        extra=("algo.ensembles.n=2", "algo.world_model.discrete_size=4"),
    )
    fabric = tiny_fabric()
    agent, ensembles, params = build_agent(
        fabric, (4,), False, cfg, tiny_obs_space(), jax.random.PRNGKey(0)
    )
    txs = build_txs(cfg)  # same six-group layout as P2E-DV1
    opt_state = {name: txs[name].init(params[name]) for name in txs}
    train_phase = make_train_phase(agent, ensembles, cfg, txs)
    batch = tiny_dreamer_batch(cfg)
    args = (params, opt_state, batch, jnp.asarray(0), np.asarray(jax.random.PRNGKey(1)))
    return train_phase.train_step, args


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    cfg.env.frame_stack = 1

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    num_envs = int(cfg.env.num_envs)
    envs = vectorized_env(
        [
            partial(
                RestartOnException,
                make_env(
                    cfg,
                    cfg.seed + rank * num_envs + i,
                    rank * num_envs,
                    log_dir if rank == 0 else None,
                    "train",
                    vector_env_idx=i,
                ),
            )
            for i in range(num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, ensembles, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        agent_key,
        state["agent"] if state else None,
    )
    player = PlayerDV2(agent, num_envs, cnn_keys, mlp_keys)
    actor_type = cfg.algo.player.actor_type

    # shared with P2E-DV1 and the AOT registry — one six-group construction
    txs = build_txs(cfg)
    opt_state = {
        "world_model": txs["world_model"].init(params["world_model"]),
        "actor_task": txs["actor_task"].init(params["actor_task"]),
        "critic_task": txs["critic_task"].init(params["critic_task"]),
        "actor_exploration": txs["actor_exploration"].init(params["actor_exploration"]),
        "critic_exploration": txs["critic_exploration"].init(params["critic_exploration"]),
        "ensembles": txs["ensembles"].init(params["ensembles"]),
    }
    if state is not None:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // int(num_envs * world_size) if not cfg.dry_run else 8
    buffer_type = cfg.buffer.get("type", "sequential").lower()
    if buffer_type == "sequential":
        rb = EnvIndependentReplayBuffer(
            buffer_size,
            n_envs=num_envs,
            obs_keys=tuple(obs_keys),
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
            buffer_cls=SequentialReplayBuffer,
        )
    elif buffer_type == "episode":
        rb = EpisodeBuffer(
            buffer_size,
            minimum_episode_length=1 if cfg.dry_run else cfg.algo.per_rank_sequence_length,
            n_envs=num_envs,
            obs_keys=tuple(obs_keys),
            prioritize_ends=cfg.buffer.prioritize_ends,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )
    else:
        raise ValueError(f"Unrecognized buffer type: {buffer_type}")
    if state is not None and "rb" in state:
        rb = state["rb"]

    from sheeprl_tpu.parallel.sharding import build_state_shardings

    train_phase = make_train_phase(
        agent, ensembles, cfg, txs,
        state_shardings=build_state_shardings(fabric, params, opt_state),
    )

    act = ActPlacement(fabric, lambda p: player_params(p, actor_type))
    act_params = act.view(params)
    key = act.place(key)

    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * num_envs if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(num_envs * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state is not None:
        ratio.load_state_dict(state["ratio"])

    # replay hot path: async prefetcher (sampling + sharded staging off-thread) or the
    # exact inline path when buffer.prefetch.enabled=false. Built AFTER the resume
    # block above so a restored batch size shapes the staged units.
    sampler = make_replay_sampler(
        rb,
        cfg.buffer.get("prefetch"),
        sample_kwargs=dict(
            batch_size=cfg.algo.per_rank_batch_size * world_size,
            sequence_length=cfg.algo.per_rank_sequence_length,
        ),
        uint8_keys=cnn_keys,
        sharding=fabric.sharding(None, None, "data") if fabric.num_devices > 1 else None,
        name="p2e-dv2-exp-replay-prefetch",
    )
    telemetry.attach_sampler(sampler)

    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    expl_cfg = agent.actor_cfg

    def expl_amount(step: int) -> float:
        amount = expl_cfg["expl_amount"]
        if expl_cfg["expl_decay"]:
            amount = amount * (0.5 ** (step / expl_cfg["expl_decay"]))
        return max(amount, expl_cfg["expl_min"])

    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states()

    cumulative_per_rank_gradient_steps = 0
    train_step = 0
    last_train = 0
    act_dim = int(np.sum(actions_dim))

    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and state is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    per_dim = actions.reshape(num_envs, len(actions_dim)).T
                    actions = np.concatenate(
                        [np.eye(dim, dtype=np.float32)[act] for act, dim in zip(per_dim, actions_dim)],
                        axis=-1,
                    )
            else:
                jobs = prepare_obs(fabric, obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
                actions, key = player.get_actions(
                    act_params, jobs, key, expl_amount=expl_amount(policy_step)
                )
                actions = np.asarray(actions)
                if is_continuous:
                    real_actions = actions
                else:
                    splits = np.cumsum(actions_dim)[:-1]
                    real_actions = np.stack(
                        [b.argmax(-1) for b in np.split(actions, splits, axis=-1)], axis=-1
                    )

            step_data["actions"] = actions.reshape((1, num_envs, -1)).astype(np.float32)
            sampler.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])
        if "restart_on_exception" in infos:
            # surface the RestartOnException crash-restart (previously invisible)
            telemetry.observe_env_restart(int(np.sum(infos["restart_on_exception"])))

        ep_info = infos.get("final_info", infos)
        if (cfg.metric.log_level > 0 or telemetry.enabled) and "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
        final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
        if final_obs_arr is not None:
            for idx in range(num_envs):
                if final_obs_arr[idx] is not None:
                    for k in obs_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])

        for k in obs_keys:
            step_data[k] = np.asarray(next_obs[k])[np.newaxis]
        obs = next_obs

        rewards = np.asarray(rewards, dtype=np.float32).reshape((1, num_envs, -1))
        step_data["terminated"] = np.asarray(terminated, np.float32).reshape((1, num_envs, -1))
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape((1, num_envs, -1))
        step_data["rewards"] = clip_rewards_fn(rewards)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (real_next_obs[k][dones_idxes])[np.newaxis]
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, reset_envs, act_dim), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            sampler.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            step_data["rewards"][:, dones_idxes] = 0.0
            step_data["terminated"][:, dones_idxes] = 0.0
            step_data["truncated"][:, dones_idxes] = 0.0
            step_data["is_first"][:, dones_idxes] = 1.0
            player.init_states(reset_envs=dones_idxes)

        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(per_rank_gradient_steps)
                    key, train_key = jax.random.split(key)
                    # one-shot injected learning pathology (resilience.fault=
                    # lr_spike): identity unless armed this iteration
                    params = apply_armed_learn_fault(params)
                    params, opt_state, metrics = train_phase(
                        params,
                        opt_state,
                        data,
                        jnp.asarray(cumulative_per_rank_gradient_steps),
                        np.asarray(train_key),
                    )
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                    act_params = act.view(params)
                    telemetry.observe_train(per_rank_gradient_steps, metrics)
                    telemetry.observe_learn(metrics)
                    if telemetry.wants_program("train_step"):
                        batch_avals = unit_avals(data)
                        telemetry.register_program(
                            "train_step",
                            train_phase.train_step,
                            (
                                params,
                                opt_state,
                                batch_avals,
                                jnp.asarray(cumulative_per_rank_gradient_steps),
                                jnp.asarray(train_key),
                            ),
                            units=1,
                        )
                    if aggregator and not aggregator.disabled:
                        for mk, mv in metrics.items():
                            aggregator.update(mk, float(np.asarray(mv)))

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step
            last_train = train_step

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            # quiesce the prefetch worker so the pickled buffer (incl. its RNG
            # state) is not a torn mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    sampler.close()
    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(player, act_params, fabric, cfg, log_dir, greedy=False)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
