"""Plan2Explore DV2 — finetuning phase (capability parity with
sheeprl/algos/p2e_dv2/p2e_dv2_finetuning.py): resume the exploration checkpoint's
world model and task heads, optionally inherit the exploration replay buffer, act
with the exploration actor during the prefill, then train the task heads with the
standard Dreamer-V2 program."""

from __future__ import annotations

import pathlib
from typing import Any, Dict

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2 import dreamer_v2 as dv2
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm()
def main(fabric, cfg: Dict[str, Any], exploration_cfg: Dict[str, Any]):
    ckpt_path = pathlib.Path(cfg.checkpoint.exploration_ckpt_path)
    resume = cfg.checkpoint.resume_from is not None
    state = fabric.load(pathlib.Path(cfg.checkpoint.resume_from) if resume else ckpt_path)

    # models/env identity must match the exploration phase (reference
    # p2e_dv2_finetuning.py:40-70)
    for k in (
        "gamma", "lmbda", "horizon", "layer_norm", "dense_units", "mlp_layers", "dense_act",
        "cnn_act", "world_model", "actor", "critic", "cnn_keys", "mlp_keys",
    ):
        if k in exploration_cfg.algo:
            cfg.algo[k] = exploration_cfg.algo[k]
    cfg.env.clip_rewards = exploration_cfg.env.clip_rewards
    if cfg.buffer.get("load_from_exploration", False) and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs

    # remap the p2e pytree into the DV2 layout: the task heads get finetuned; the
    # exploration actor only drives the prefill
    agent_state = jax.tree_util.tree_map(jnp.asarray, state["agent"])
    dv2_state = dict(state)
    exploration_actor_params = None
    if "actor_task" in agent_state:
        # p2e layout (exploration checkpoint) → remap to DV2 layout
        dv2_state["agent"] = {
            "world_model": agent_state["world_model"],
            "actor": agent_state["actor_task"],
            "critic": agent_state["critic_task"],
            "target_critic": agent_state["target_critic_task"],
        }
        if cfg.algo.player.actor_type == "exploration":
            exploration_actor_params = agent_state["actor_exploration"]
    else:
        # already DV2 layout: resuming an interrupted finetuning checkpoint
        dv2_state["agent"] = agent_state
    if not resume:
        # fresh finetuning: counters restart; only the agent (and optionally the
        # buffer) carry over — the guarded dv2.main skips the missing keys
        for k in ("iter_num", "last_log", "last_checkpoint"):
            dv2_state[k] = 0
        dv2_state["batch_size"] = cfg.algo.per_rank_batch_size * fabric.world_size
        dv2_state.pop("opt_state", None)
        dv2_state.pop("ratio", None)
        if not cfg.buffer.get("load_from_exploration", False):
            dv2_state.pop("rb", None)

    _orig_load = fabric.load
    fabric.load = lambda path: dv2_state
    cfg.checkpoint.resume_from = cfg.checkpoint.resume_from or str(ckpt_path)
    try:
        dv2.main(fabric, cfg, exploration_actor_params=exploration_actor_params)
    finally:
        fabric.load = _orig_load
