"""Plan2Explore (Dreamer-V2 backbone) agent (reference sheeprl/algos/p2e_dv2/agent.py):
DV2 world model + disagreement ensemble + exploration actor/critic (with target)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v2.agent import DV2Agent
from sheeprl_tpu.algos.dreamer_v2.agent import build_agent as build_dv2_agent
from sheeprl_tpu.algos.p2e_dv3.agent import EnsembleHeads


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV2Agent, EnsembleHeads, Dict[str, Any]]:
    k_dv2, k_expl, k_ens, k_crit = jax.random.split(key, 4)
    agent, dv2_params = build_dv2_agent(fabric, actions_dim, is_continuous, cfg, obs_space, k_dv2)

    latent = jnp.zeros((1, agent.latent_state_size), jnp.float32)
    actor_exploration_params = agent.actor.init(k_expl, latent)["params"]
    critic_exploration_params = agent.critic.init(k_crit, latent)["params"]

    ens_cfg = cfg.algo.ensembles
    ensembles = EnsembleHeads(
        n=int(ens_cfg.n),
        units=ens_cfg.dense_units,
        n_layers=ens_cfg.mlp_layers,
        output_dim=agent.stoch_state_size,
        activation=ens_cfg.dense_act,
        dtype=fabric.compute_dtype,
    )
    act_dim = int(np.sum(actions_dim))
    ens_in = jnp.zeros((1, agent.latent_state_size + act_dim), jnp.float32)
    ensembles_params = ensembles.init(k_ens, ens_in)["params"]

    params = {
        "world_model": dv2_params["world_model"],
        "actor_task": dv2_params["actor"],
        "critic_task": dv2_params["critic"],
        "target_critic_task": dv2_params["target_critic"],
        "actor_exploration": actor_exploration_params,
        "critic_exploration": critic_exploration_params,
        "target_critic_exploration": jax.tree_util.tree_map(jnp.copy, critic_exploration_params),
        "ensembles": ensembles_params,
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    return agent, ensembles, params


def player_params(params: Dict[str, Any], actor_type: str) -> Dict[str, Any]:
    return {
        "world_model": params["world_model"],
        "actor": params["actor_exploration"] if actor_type == "exploration" else params["actor_task"],
    }
