"""A2C, Anakin topology: on-device envs with one fused rollout+GAE+update
program per iteration — one accumulated full-rollout gradient step, a2c losses
(see ``algos/ppo/anakin.py`` for the shared driver; ``algos/a2c/a2c.py`` is the
host-env reference semantics)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.ppo.anakin import run_anakin
from sheeprl_tpu.utils.registry import register_algorithm


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    run_anakin(fabric, cfg)
