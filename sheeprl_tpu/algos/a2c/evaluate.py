"""A2C evaluation entrypoint (reference: sheeprl/algos/a2c/evaluate.py:1-60).

A2C shares PPO's agent surface (vector-MLP actor-critic; the reference's A2CAgent is
its own torch module, a2c/agent.py:48), so evaluation reuses PPO's ``evaluate`` body
and only adds the registry binding."""

from __future__ import annotations

from sheeprl_tpu.algos.ppo.evaluate import evaluate as _ppo_evaluate
from sheeprl_tpu.utils.registry import register_evaluation

evaluate = register_evaluation(algorithms=["a2c", "a2c_anakin"])(_ppo_evaluate)
