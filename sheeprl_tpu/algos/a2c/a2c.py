"""A2C, coupled training (capability parity with sheeprl/algos/a2c/a2c.py:30-383).

The reference accumulates gradients over minibatches and steps once per rollout
(a2c.py:63-96); in JAX that collapses into a single jitted full-rollout update —
with ``loss_reduction=sum`` (the A2C default) the math is identical, with fewer
dispatches and one fused XLA program. Under the ``dp`` strategy the rollout batch is
sharded over the mesh ``data`` axis and XLA inserts the gradient psum.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.ppo.agent import build_agent, policy_output
from sheeprl_tpu.algos.ppo.utils import normalize_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, gae, save_configs


@register_algorithm(decoupled=False)
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    total_num_envs = int(cfg.env.num_envs * world_size)
    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * total_num_envs + i,
                rank * total_num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(total_num_envs)
        ],
        # same-step autoreset restores the reference's gymnasium-0.x semantics: the
        # final observation of a done episode arrives in infos["final_obs"] and the
        # post-done row is a real reset transition, so truncation bootstrapping works
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `algo.mlp_keys.encoder=[state]`")
    # A2C is vector-only (reference a2c.py)
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the A2C agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}"
            )
    cfg.algo.cnn_keys.encoder = []
    obs_keys = cfg.algo.mlp_keys.encoder

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state["agent"])

    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(total_num_envs * cfg.algo.rollout_steps)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1

    tx = instantiate(cfg.algo.optimizer)
    if cfg.algo.max_grad_norm > 0.0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.algo.max_grad_norm), tx)
    opt_state = tx.init(params)
    if state is not None and "optimizer" in state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["optimizer"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb = ReplayBuffer(
        cfg.buffer.size,
        total_num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=obs_keys,
    )

    loss_reduction = cfg.algo.loss_reduction

    # same latency design as PPO: act path on the host CPU backend, one fused jitted
    # device program per iteration (GAE + full-rollout accumulated update)
    act = ActPlacement(fabric)
    act_on_cpu = act.on_cpu

    @jax.jit
    def policy_step_fn(params, obs: Dict[str, jax.Array], key):
        # PRNG chain advances inside the jitted program — an un-jitted per-step
        # jax.random.split costs ~0.5 ms of host dispatch
        key, step_key = jax.random.split(key)
        norm_obs = {k: v.astype(jnp.float32) for k, v in obs.items()}
        actor_outs, values = agent.apply({"params": params}, norm_obs)
        out = policy_output(actor_outs, values, step_key, actions_dim, is_continuous)
        if is_continuous:
            real_actions = out["actions"]
        else:
            split = jnp.split(out["actions"], np.cumsum(actions_dim)[:-1].tolist(), axis=-1)
            real_actions = jnp.stack([s.argmax(axis=-1) for s in split], axis=-1)
        # one packed array -> one device-to-host conversion per step (same trick
        # as ppo.py's policy_step_fn; A2C stores values + actions only)
        packed = jnp.concatenate([out["values"], out["actions"]], axis=-1).astype(jnp.float32)
        return packed, real_actions, key

    @jax.jit
    def get_values(params, obs: Dict[str, jax.Array]):
        obs = {k: v.astype(jnp.float32) for k, v in obs.items()}
        _, values = agent.apply({"params": params}, obs)
        return values

    # compile the Learn/* stats only when the telemetry learning plane is on
    learn_on = learn_stats.enabled(cfg)

    def loss_fn(params, batch):
        obs = {k: batch[k] for k in obs_keys}
        actor_outs, values = agent.apply({"params": params}, obs)
        out = policy_output(
            actor_outs, values, jax.random.PRNGKey(0), actions_dim, is_continuous, actions=batch["actions"]
        )
        pg = policy_loss(out["logprob"], batch["advantages"], loss_reduction)
        vl = value_loss(out["values"], batch["returns"], loss_reduction)
        # learn-stats aux (scalars only): value statistics, value residual vs
        # the GAE return, policy entropy (utils/learn_stats.py)
        stats = learn_stats.maybe(learn_on, lambda: {
            **learn_stats.value_stats(jax.lax.stop_gradient(out["values"])),
            **learn_stats.td_quantiles(jax.lax.stop_gradient(batch["returns"] - out["values"])),
            **learn_stats.entropy_stats(jax.lax.stop_gradient(out["entropy"])),
        })
        return pg + vl, (pg, vl, stats)

    # out_shardings pins the state outputs on multi-device meshes — see the
    # ppo make_train_phase note (PR 8 residual; build_state_shardings)
    from functools import partial

    from sheeprl_tpu.parallel.sharding import build_state_shardings

    _state_shardings = build_state_shardings(fabric, params, opt_state)
    _train_jit_kwargs = (
        {"out_shardings": tuple(_state_shardings)} if _state_shardings is not None else {}
    )

    @partial(jax.jit, **_train_jit_kwargs)
    def train_phase(params, opt_state, data, next_values):
        returns, advantages = gae(
            data["rewards"],
            data["values"],
            data["dones"],
            next_values,
            cfg.algo.rollout_steps,
            cfg.algo.gamma,
            cfg.algo.gae_lambda,
        )
        batch = {k: v.reshape(-1, *v.shape[2:]) for k, v in data.items()}
        batch["returns"] = returns.reshape(-1, 1)
        batch["advantages"] = advantages.reshape(-1, 1)
        grads, (pg, vl, stats) = jax.grad(loss_fn, has_aux=True)(params, batch)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # the Learn/ keys ride the metrics dict (RunTelemetry.observe_learn
        # extracts them — utils/learn_stats.py); a2c's tx has no clip transform
        metrics = {
            "pg": pg,
            "vl": vl,
            **stats,
            **learn_stats.maybe(learn_on, lambda: {
                **learn_stats.group_stats(
                    "policy", grads=grads, updates=updates, params=new_params, opt_state=new_opt_state
                ),
                "Learn/loss/policy": pg,
                "Learn/loss/value": vl,
            }),
        }
        return new_params, new_opt_state, metrics

    if world_size > 1:
        params = fabric.replicate_pytree(params)
        opt_state = fabric.replicate_pytree(opt_state)
    act_params = act.view(params)
    key = act.place(key)

    step_data: Dict[str, np.ndarray] = {}
    next_obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = next_obs[k][np.newaxis]

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/env_interaction_time"):
            for _ in range(cfg.algo.rollout_steps):
                policy_step += total_num_envs

                obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
                packed, real_actions, key = policy_step_fn(act_params, obs_host, key)
                real_actions_np = np.asarray(real_actions)
                if is_continuous:
                    env_actions = real_actions_np.reshape(envs.action_space.shape)
                else:
                    env_actions = real_actions_np.reshape(
                        (total_num_envs, -1) if is_multidiscrete else (total_num_envs,)
                    )

                obs, rewards, terminated, truncated, info = envs.step(env_actions)
                dones = np.logical_or(terminated, truncated).reshape(total_num_envs, 1).astype(np.float32)
                rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, 1)

                # truncation bootstrap (reference a2c.py:250-270): add gamma*V(final_obs)
                if "final_obs" in info or "final_observation" in info:
                    final_obs_arr = info.get("final_obs", info.get("final_observation"))
                    truncated_envs = np.nonzero(truncated)[0]
                    if len(truncated_envs) > 0:
                        real_next_obs = {
                            k: np.stack(
                                [np.asarray(final_obs_arr[i][k], dtype=np.float32) for i in truncated_envs]
                            )
                            for k in obs_keys
                        }
                        vals = np.asarray(get_values(act_params, real_next_obs)).reshape(-1, 1)
                        rewards[truncated_envs] += cfg.algo.gamma * vals

                packed_np = np.asarray(packed)
                step_data["dones"] = dones[np.newaxis]
                step_data["values"] = packed_np[:, :1][np.newaxis]
                step_data["actions"] = packed_np[:, 1:][np.newaxis]
                step_data["rewards"] = rewards[np.newaxis]
                if cfg.buffer.memmap:
                    step_data["returns"] = np.zeros_like(rewards)[np.newaxis]
                    step_data["advantages"] = np.zeros_like(rewards)[np.newaxis]
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                next_obs = obs
                for k in obs_keys:
                    step_data[k] = obs[k][np.newaxis]

                # under SAME_STEP autoreset the done-step infos arrive in final_info
                ep_info = info.get("final_info", info)
                if "episode" in ep_info:
                    ep = ep_info["episode"]
                    mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
                    rews, lens = ep["r"][mask], ep["l"][mask]
                    if len(rews) > 0:
                        telemetry.observe_episodes(rews, lens)
                        if aggregator and not aggregator.disabled:
                            aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                            aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        obs_host = {k: np.asarray(next_obs[k], dtype=np.float32) for k in obs_keys}
        next_values = np.asarray(get_values(act_params, obs_host))

        with timer("Time/train_time"):
            data = {k: np.asarray(rb[k]) for k in rb.buffer.keys() if k not in ("returns", "advantages")}
            if world_size > 1:
                data = jax.device_put(data, fabric.sharding(None, "data"))
            # one-shot injected learning pathology (resilience.fault=lr_spike):
            # identity unless the fault armed this iteration
            params = apply_armed_learn_fault(params)
            params, opt_state, metrics = train_phase(params, opt_state, data, next_values)
            act_params = act.view(params)
            telemetry.observe_train(1, metrics)
            telemetry.observe_learn(metrics)
            if telemetry.wants_program("train_phase"):
                telemetry.register_program(
                    "train_phase", train_phase, (params, opt_state, data, next_values), units=1
                )
            if aggregator and not aggregator.disabled:
                aggregator.update("Loss/policy_loss", np.asarray(metrics["pg"]))
                aggregator.update("Loss/value_loss", np.asarray(metrics["vl"]))

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "optimizer": opt_state,
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            with timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(agent.apply, params, fabric, cfg, log_dir)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
