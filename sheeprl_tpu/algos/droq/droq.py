"""DroQ, coupled training (capability parity with sheeprl/algos/droq/droq.py:30-436).

DroQ = SAC with Dropout+LayerNorm critics driven at a high replay ratio
(arXiv:2110.02034). Per train call the reference runs G critic minibatch updates
(one per gradient step, each critic updated on its own MSE with target-EMA after
every member update, droq.py:94-120) and then a single actor + alpha update on a
separate batch (droq.py:122-137, with the Q mean — not min — in the policy loss).

TPU-native structure (same stance as sac.py):
- the replay batch for the critics is sampled as ``[G, B, ...]`` on the host,
  uploaded once, and a ``lax.scan`` walks the G critic updates in ONE device
  program; the actor/alpha updates run in the same program after the scan;
- the per-member critic MSEs are computed on the vmapped ensemble in one pass —
  summing them gives each member exactly its own gradient (params are disjoint),
  so the reference's sequential per-member stepping collapses into one fused
  optax update; the per-member EMA after each member's update is then identical
  to one EMA after the fused update;
- dropout stays active on online AND target critics during training (torch
  modules run in train mode throughout the reference train()).
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.droq.agent import build_agent
from sheeprl_tpu.algos.sac.agent import squash_and_logprob
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss

# DroQ's optimizer/opt-state layout is SAC's (same actor/critic/alpha triple,
# same config keys) — one construction, shared with the AOT registry
from sheeprl_tpu.algos.sac.sac import build_optimizers, init_opt_state
from sheeprl_tpu.algos.sac.utils import prepare_obs, test
from sheeprl_tpu.analysis.programs import register_fused_program
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, Ratio, save_configs


def make_train_phase(cfg, actor, critic, target_entropy, txs=None, jit_kwargs=None):
    """Build the fused DroQ train program: G critic updates via ``lax.scan``
    (EMA folded into each step), then a single actor + alpha update — the whole
    reference train() (droq.py:30-137) as one device program. ONE factory
    shared by the loop and the AOT contract registry. ``jit_kwargs`` carries the
    multi-device ``out_shardings`` pin (see the donation note below)."""
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    num_critics = int(cfg.algo.critic.n)
    action_scale = jnp.asarray(actor.action_scale, dtype=jnp.float32)
    action_bias = jnp.asarray(actor.action_bias, dtype=jnp.float32)
    txs = txs if txs is not None else build_optimizers(cfg)
    actor_tx, critic_tx, alpha_tx = txs["actor"], txs["critic"], txs["alpha"]
    # compile the Learn/* stats only when the telemetry learning plane is on
    learn_on = learn_stats.enabled(cfg)

    def critic_loss_fn(critic_params, other, batch, step_key):
        k_pi, k_tgt, k_online = jax.random.split(step_key, 3)
        next_obs = batch["next_observations"]
        mean, std = actor.apply({"params": other["actor"]}, next_obs)
        next_actions, next_logprobs = squash_and_logprob(mean, std, k_pi, action_scale, action_bias)
        # dropout stays on for the target ensemble too (reference modules are in
        # train mode inside train(), droq.py:94-99)
        target_q = critic.apply(
            {"params": other["target_critic"]}, next_obs, next_actions, False, rngs={"dropout": k_tgt}
        )
        alpha = jnp.exp(other["log_alpha"])
        min_target = jnp.min(target_q, axis=-1, keepdims=True) - alpha * next_logprobs
        next_qf_value = batch["rewards"] + (1 - batch["terminated"]) * gamma * min_target
        qf_values = critic.apply(
            {"params": critic_params}, batch["observations"], batch["actions"], False, rngs={"dropout": k_online}
        )
        loss = critic_loss(qf_values, jax.lax.stop_gradient(next_qf_value), num_critics)
        # aux for the learn-stats block: Q statistics + per-sample TD error
        return loss, (qf_values, qf_values - next_qf_value)

    def actor_loss_fn(actor_params, other, batch, step_key):
        k_pi, k_q = jax.random.split(step_key)
        mean, std = actor.apply({"params": actor_params}, batch["observations"])
        actions, logprobs = squash_and_logprob(mean, std, k_pi, action_scale, action_bias)
        qf_values = critic.apply(
            {"params": other["critic"]}, batch["observations"], actions, False, rngs={"dropout": k_q}
        )
        # DroQ uses the ensemble MEAN in the policy loss (reference droq.py:124)
        mean_qf = jnp.mean(qf_values, axis=-1, keepdims=True)
        alpha = jnp.exp(jax.lax.stop_gradient(other["log_alpha"]))
        return policy_loss(alpha, logprobs, mean_qf), logprobs

    def alpha_loss_fn(log_alpha, logprobs):
        return entropy_loss(log_alpha, jax.lax.stop_gradient(logprobs), target_entropy)

    # donate_argnums: XLA reuses the params/opt-state buffers in place instead of
    # copying the whole train state every round (callers always rebind to the
    # returned trees, so the invalidated inputs are never read again).
    # out_shardings (via jit_kwargs) pins the state outputs on multi-device
    # meshes — see the sac.py note (PR 8 residual; build_state_shardings).
    @partial(jax.jit, donate_argnums=(0, 1), **(jit_kwargs or {}))
    def train_phase(params, opt_state, critic_data, actor_data, train_key):
        """G critic updates via lax.scan (EMA folded into each step), then a single
        actor + alpha update — the whole reference train() (droq.py:30-137) as one
        device program."""

        def critic_step(carry, inp):
            params, opt_state = carry
            batch, k = inp
            (qf_loss, (qf_values, td_error)), qf_grads = jax.value_and_grad(
                critic_loss_fn, has_aux=True
            )(params["critic"], params, batch, k)
            updates, new_copt = critic_tx.update(qf_grads, opt_state["critic"], params["critic"])
            params = {**params, "critic": optax.apply_updates(params["critic"], updates)}
            opt_state = {**opt_state, "critic": new_copt}
            params = {
                **params,
                "target_critic": jax.tree_util.tree_map(
                    lambda t, c: t * (1 - tau) + c * tau, params["target_critic"], params["critic"]
                ),
            }
            critic_learn = learn_stats.maybe(learn_on, lambda: {
                **learn_stats.group_stats(
                    "critic",
                    grads=qf_grads,
                    updates=updates,
                    params=params["critic"],
                    opt_state=new_copt,
                ),
                **learn_stats.value_stats(qf_values, prefix="q"),
                **learn_stats.td_quantiles(td_error),
            })
            return (params, opt_state), (qf_loss, critic_learn)

        G = critic_data["rewards"].shape[0]
        k_scan, k_actor = jax.random.split(train_key)
        keys = jax.random.split(k_scan, G)
        (params, opt_state), (qf_losses, critic_learn) = jax.lax.scan(
            critic_step, (params, opt_state), (critic_data, keys)
        )

        (a_loss, logprobs), a_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            params["actor"], params, actor_data, k_actor
        )
        a_updates, new_aopt = actor_tx.update(a_grads, opt_state["actor"], params["actor"])
        params = {**params, "actor": optax.apply_updates(params["actor"], a_updates)}
        opt_state = {**opt_state, "actor": new_aopt}

        al_loss, al_grads = jax.value_and_grad(alpha_loss_fn)(params["log_alpha"], logprobs)
        al_updates, new_alopt = alpha_tx.update(al_grads, opt_state["alpha"], params["log_alpha"])
        params = {**params, "log_alpha": optax.apply_updates(params["log_alpha"], al_updates)}
        opt_state = {**opt_state, "alpha": new_alopt}

        learn = learn_stats.maybe(learn_on, lambda: {
            **learn_stats.reduce_stacked(critic_learn),
            **learn_stats.group_stats(
                "actor", grads=a_grads, updates=a_updates, params=params["actor"], opt_state=new_aopt
            ),
            **learn_stats.group_stats("alpha", grads=al_grads),
            **learn_stats.entropy_stats(-logprobs),
            "Learn/alpha": jnp.exp(params["log_alpha"]).reshape(()),
            "Learn/loss/critic": qf_losses.mean() / num_critics,
            "Learn/loss/actor": a_loss,
            "Learn/loss/alpha": al_loss,
        })
        # log the per-member MSE (the reference logs each member's loss into a
        # MeanMetric, droq.py:113-115), not the summed ensemble loss
        return params, opt_state, jnp.stack([qf_losses.mean() / num_critics, a_loss, al_loss]), learn

    return train_phase


@register_fused_program(
    "droq.train_phase",
    min_donated=2,
    doc="fused DroQ update (scanned critic ensemble steps + actor/alpha)",
)
def _aot_train_program():
    """Tiny MLP DroQ agent through the loop's own factory."""
    from sheeprl_tpu.analysis.programs import tiny_fabric
    from sheeprl_tpu.config import compose

    cfg = compose(
        [
            "exp=droq",
            "env=dummy",
            "fabric.accelerator=cpu",
            "env.num_envs=2",
            "env.capture_video=False",
            "algo.per_rank_batch_size=4",
            "buffer.memmap=False",
            "metric.log_level=0",
            # lower the GROWN program (Learn/* stats compile in under telemetry)
            "metric.telemetry.enabled=true",
        ]
    )
    fabric = tiny_fabric()
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (8,), np.float32)})
    action_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
    actor, critic, params = build_agent(fabric, cfg, obs_space, action_space, jax.random.PRNGKey(0), None)
    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    train_phase = make_train_phase(cfg, actor, critic, target_entropy=-2.0, txs=txs)
    G, B = 1, int(cfg.algo.per_rank_batch_size)
    rng = np.random.default_rng(0)

    def _batch(leading):
        return {
            "observations": rng.normal(size=(*leading, B, 8)).astype(np.float32),
            "next_observations": rng.normal(size=(*leading, B, 8)).astype(np.float32),
            "actions": rng.normal(size=(*leading, B, 2)).astype(np.float32),
            "rewards": rng.normal(size=(*leading, B, 1)).astype(np.float32),
            "terminated": np.zeros((*leading, B, 1), np.float32),
        }

    args = (params, opt_state, _batch((G,)), _batch(()), np.asarray(jax.random.PRNGKey(1)))
    return train_phase, args


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("DroQ algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    total_num_envs = int(cfg.env.num_envs * world_size)
    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * total_num_envs + i,
                rank * total_num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(total_num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the DroQ agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if len(cfg.algo.mlp_keys.encoder) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in cfg.algo.mlp_keys.encoder:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the DroQ agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}. "
                f"Provided environment: {cfg.env.id}"
            )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder MLP keys:", cfg.algo.mlp_keys.encoder)
    mlp_keys = cfg.algo.mlp_keys.encoder

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    actor, critic, params = build_agent(
        fabric, cfg, observation_space, action_space, agent_key, state["agent"] if state else None
    )
    act_dim = int(np.prod(action_space.shape))
    target_entropy = -float(act_dim)
    action_scale = jnp.asarray(actor.action_scale, dtype=jnp.float32)
    action_bias = jnp.asarray(actor.action_bias, dtype=jnp.float32)

    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    if state is not None:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // total_num_envs if not cfg.dry_run else 1
    rb = ReplayBuffer(
        buffer_size,
        total_num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=("observations",),
    )
    if state is not None and "rb" in state:
        rb = state["rb"]

    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(total_num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state is not None:
        ratio.load_state_dict(state["ratio"])

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    # ---------------- jitted programs ----------------
    sample_next_obs = bool(cfg.buffer.sample_next_obs)

    act = ActPlacement(fabric, lambda p: p["actor"])
    act_on_cpu = act.on_cpu

    @partial(jax.jit, backend="cpu" if act_on_cpu else None)
    def act_fn(actor_params, obs: jax.Array, key):
        # PRNG chain advances inside the jitted program (un-jitted per-step
        # jax.random.split costs ~0.5 ms of host dispatch)
        key, step_key = jax.random.split(key)
        mean, std = actor.apply({"params": actor_params}, obs)
        actions, _ = squash_and_logprob(mean, std, step_key, action_scale, action_bias)
        return actions, key

    # the fused train program — ONE factory (make_train_phase) shared with the
    # AOT contract registry, so the program `sheeprl.py lint --aot` lowers is
    # the program this loop runs. out_shardings pins the state outputs on
    # multi-device meshes — see make_train_phase's donation note.
    from sheeprl_tpu.parallel.sharding import build_state_shardings

    # extra_outputs=2: the losses vector AND the Learn/* stats block
    _state_shardings = build_state_shardings(fabric, params, opt_state, extra_outputs=2)
    _train_jit_kwargs = (
        {"out_shardings": tuple(_state_shardings)} if _state_shardings is not None else {}
    )
    train_phase = make_train_phase(
        cfg, actor, critic, target_entropy, txs=txs, jit_kwargs=_train_jit_kwargs
    )

    if world_size > 1:
        params = fabric.replicate_pytree(params)
        opt_state = fabric.replicate_pytree(opt_state)
    act_params = act.view(params)
    key = act.place(key)

    # replay hot path: one async prefetcher serves BOTH streams — the critic block
    # pops G units, the actor batch is one extra unit of the same shape (identical
    # sample kwargs), keeping the buffer RNG single-consumer and deterministic
    sampler = make_replay_sampler(
        rb,
        cfg.buffer.get("prefetch"),
        sample_kwargs=dict(
            batch_size=cfg.algo.per_rank_batch_size * world_size,
            sample_next_obs=sample_next_obs,
        ),
        uint8_keys=(),  # everything float32
        sharding=fabric.sharding(None, "data") if world_size > 1 else None,
        name="droq-replay-prefetch",
    )
    telemetry.attach_sampler(sampler)

    # ---------------- main loop ----------------
    cumulative_per_rank_gradient_steps = 0
    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]

    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                flat_obs = prepare_obs(fabric, obs, mlp_keys=mlp_keys, num_envs=total_num_envs)
                actions, key = act_fn(act_params, flat_obs, key)
                actions = np.asarray(actions)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                actions.reshape(envs.action_space.shape)
            )
            rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, -1)

        ep_info = infos.get("final_info", infos)
        if "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
        final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
        if final_obs_arr is not None:
            for idx in range(total_num_envs):
                if final_obs_arr[idx] is not None:
                    for k in mlp_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])
        flat_real_next = np.concatenate(
            [real_next_obs[k].reshape(total_num_envs, -1) for k in mlp_keys], axis=-1
        ).astype(np.float32)

        step_data["terminated"] = np.asarray(terminated).reshape(1, total_num_envs, -1).astype(np.float32)
        step_data["truncated"] = np.asarray(truncated).reshape(1, total_num_envs, -1).astype(np.float32)
        step_data["actions"] = actions.reshape(1, total_num_envs, -1).astype(np.float32)
        step_data["observations"] = np.concatenate(
            [np.asarray(obs[k]).reshape(total_num_envs, -1) for k in mlp_keys], axis=-1
        ).astype(np.float32)[np.newaxis]
        if not sample_next_obs:
            step_data["next_observations"] = flat_real_next[np.newaxis]
        step_data["rewards"] = rewards[np.newaxis]
        sampler.add(step_data, validate_args=cfg.buffer.validate_args)

        obs = next_obs

        # train (reference droq.py:339-360): Ratio decides G; critics see a [G, B]
        # block, the actor a separate [B] batch
        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio((policy_step - prefill_steps + policy_steps_per_iter) / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    critic_data = sampler.sample(per_rank_gradient_steps)
                    # actor batch: one more unit of the same stream; slicing the
                    # [1, B, ...] block keeps the batch-axis sharding
                    actor_data = jax.tree_util.tree_map(lambda v: v[0], sampler.sample(1))
                    key, train_key = jax.random.split(key)
                    # one-shot injected learning pathology (resilience.fault=
                    # lr_spike): identity unless armed this iteration
                    params = apply_armed_learn_fault(params)
                    params, opt_state, mean_losses, learn = train_phase(
                        params, opt_state, critic_data, actor_data, np.asarray(train_key)
                    )
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    act_params = act.view(params)
                    telemetry.observe_train(per_rank_gradient_steps, mean_losses)
                    telemetry.observe_learn(learn)
                    if telemetry.wants_program("train_phase"):
                        telemetry.register_program(
                            "train_phase",
                            train_phase,
                            (params, opt_state, critic_data, actor_data, np.asarray(train_key)),
                            units=per_rank_gradient_steps,
                        )
                    if aggregator and not aggregator.disabled:
                        losses_np = np.asarray(mean_losses)
                        aggregator.update("Loss/value_loss", losses_np[0])
                        aggregator.update("Loss/policy_loss", losses_np[1])
                        aggregator.update("Loss/alpha_loss", losses_np[2])

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (policy_step - last_log) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (policy_step - last_log)
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            # quiesce the prefetch worker so the pickled buffer (incl. its RNG
            # state) is not a torn mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    sampler.close()
    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(actor.apply, params["actor"], fabric, cfg, log_dir)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
