"""DroQ helpers (reference: sheeprl/algos/droq/utils.py — reuses the SAC toolbox)."""

from __future__ import annotations

from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test

__all__ = ["AGGREGATOR_KEYS", "MODELS_TO_REGISTER", "prepare_obs", "test"]
