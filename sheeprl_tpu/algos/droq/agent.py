"""DroQ agent, Flax-native.

Capability parity with the reference agent (sheeprl/algos/droq/agent.py:20-278):
the SAC tanh-Gaussian actor plus a critic ensemble whose members are two-layer MLPs
with Dropout + LayerNorm after every hidden projection (arXiv:2110.02034, reference
DROQCritic at agent.py:20-61).

TPU-native structure mirrors the SAC agent: the ensemble is one vmapped module with
stacked params — a single apply evaluates every critic as batched MXU matmuls, with
per-member dropout RNG streams (the reference loops over n separate torch modules).
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import SACActor
from sheeprl_tpu.models.models import MLP


class DROQCritic(nn.Module):
    """Q(s, a) MLP with Dropout + LayerNorm per hidden layer (reference
    droq/agent.py:20-61: Dense -> Dropout -> LayerNorm -> ReLU)."""

    hidden_size: int = 256
    num_critics: int = 1
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array, deterministic: bool = True) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        return MLP(
            hidden_sizes=(self.hidden_size, self.hidden_size),
            output_dim=self.num_critics,
            activation="relu",
            layer_norm=True,
            dropout=self.dropout,
            dtype=self.dtype,
        )(x, deterministic=deterministic)


class DROQCriticEnsemble(nn.Module):
    """n independent DroQ critics with stacked params, one vmapped apply →
    [*batch, n]; dropout RNG is split per member so each critic sees its own mask."""

    n: int
    hidden_size: int = 256
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array, deterministic: bool = True) -> jax.Array:
        ensemble = nn.vmap(
            DROQCritic,
            in_axes=None,
            out_axes=-1,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            axis_size=self.n,
        )
        out = ensemble(
            hidden_size=self.hidden_size, num_critics=1, dropout=self.dropout, dtype=self.dtype
        )(obs, action, deterministic)
        return out.reshape(*out.shape[:-2], self.n)


def build_agent(
    fabric,
    cfg,
    observation_space,
    action_space,
    key: jax.Array,
    state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACActor, DROQCriticEnsemble, Dict[str, Any]]:
    """Create modules + the params pytree {actor, critic, target_critic, log_alpha}
    (role of reference build_agent, sheeprl/algos/droq/agent.py:212-278)."""
    obs_dim = sum(prod(observation_space[k].shape) for k in cfg.algo.mlp_keys.encoder)
    act_dim = int(prod(action_space.shape))
    actor = SACActor(
        action_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        action_low=tuple(np.asarray(action_space.low, dtype=np.float32).reshape(-1).tolist()),
        action_high=tuple(np.asarray(action_space.high, dtype=np.float32).reshape(-1).tolist()),
        dtype=fabric.compute_dtype,
    )
    critic = DROQCriticEnsemble(
        n=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dropout=cfg.algo.critic.dropout,
        dtype=fabric.compute_dtype,
    )
    k_actor, k_critic = jax.random.split(key)
    dummy_obs = jnp.zeros((1, obs_dim), dtype=jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), dtype=jnp.float32)
    actor_params = actor.init(k_actor, dummy_obs)["params"]
    critic_params = critic.init(k_critic, dummy_obs, dummy_act)["params"]
    params = {
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
        "log_alpha": jnp.log(jnp.asarray([cfg.algo.alpha.alpha], dtype=jnp.float32)),
    }
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state)
    return actor, critic, params
