"""Dreamer-V1, coupled training (capability parity with
sheeprl/algos/dreamer_v1/dreamer_v1.py:96-750).

Same TPU-native shape as the other Dreamer modules: one jitted program per iteration
scanning the ``[G, T, B, ...]`` replay block — Gaussian-latent dynamic scan,
world-model update (single KL with free nats), H-step imagination, dynamics-
backprop actor update (-mean(discount * lambda)), Normal(.,1) critic update."""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v1.agent import DV1Agent, PlayerDV1, build_agent
from sheeprl_tpu.algos.dreamer_v1.loss import reconstruction_loss
from sheeprl_tpu.analysis.programs import register_fused_program
from sheeprl_tpu.algos.dreamer_v1.utils import compute_lambda_values, prepare_obs, test
from sheeprl_tpu.algos.dreamer_v2.utils import (
    _HALF_LOG_2PI,
    bernoulli_logprob as _bernoulli_logprob,
    normal1_logprob as _normal1_logprob,
)
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.envs.wrappers import RestartOnException
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.mfu import unit_avals
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, BenchWindow, Ratio, foreach_gradient_step, save_configs


def make_train_phase(agent: DV1Agent, cfg, world_tx, actor_tx, critic_tx, state_shardings=None):
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    wm_cfg = cfg.algo.world_model
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    horizon = int(cfg.algo.horizon)
    use_continues = bool(wm_cfg.use_continues)
    # compile the Learn/* stats only when the telemetry learning plane is on
    learn_on = learn_stats.enabled(cfg)
    # static clip thresholds for the learn-stats post-clip norms (the txs chain
    # clip_by_global_norm with exactly these values — dv3.build_optimizers)
    clips = {
        "world_model": float(cfg.algo.world_model.clip_gradients or 0) or None,
        "actor": float(cfg.algo.actor.clip_gradients or 0) or None,
        "critic": float(cfg.algo.critic.clip_gradients or 0) or None,
    }

    def world_loss_fn(wm_params, batch, key):
        batch_obs = {k: batch[k] / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: batch[k] for k in mlp_keys})
        # row t stores the action chosen *at* o_t; the dynamics consume the action
        # that *led to* o_t (same shift as dreamer_v3.py, reference dv3:219-221)
        actions = jnp.concatenate(
            [jnp.zeros_like(batch["actions"][:1]), batch["actions"][:-1]], axis=0
        )
        embedded = agent.encoder.apply({"params": wm_params["encoder"]}, batch_obs)
        hs, zs, post_mean, post_std, prior_mean, prior_std = agent.dynamic_scan(
            wm_params, embedded, actions, key
        )
        latents = jnp.concatenate([zs, hs], axis=-1)
        recon = agent.observation_model.apply({"params": wm_params["observation_model"]}, latents)
        obs_lps = {
            k: _normal1_logprob(recon[k], batch_obs[k], len(recon[k].shape[2:]))
            for k in cnn_dec_keys + mlp_dec_keys
        }
        reward_pred = agent.reward_model.apply({"params": wm_params["reward_model"]}, latents)
        reward_lp = _normal1_logprob(reward_pred, batch["rewards"], 1)
        cont_lp = None
        if use_continues:
            cont_logits = agent.continue_model.apply({"params": wm_params["continue_model"]}, latents)
            cont_lp = _bernoulli_logprob(cont_logits, (1.0 - batch["terminated"]) * gamma, 1)
        loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
            obs_lps,
            reward_lp,
            post_mean,
            post_std,
            prior_mean,
            prior_std,
            kl_free_nats=wm_cfg.kl_free_nats,
            kl_regularizer=wm_cfg.kl_regularizer,
            continue_log_prob=cont_lp,
            continue_scale_factor=wm_cfg.continue_scale_factor,
        )

        def _normal_entropy(std):
            return (0.5 + _HALF_LOG_2PI + jnp.log(std)).sum(-1).mean()

        metrics = {
            "Loss/world_model_loss": loss,
            "Loss/observation_loss": observation_loss,
            "Loss/reward_loss": reward_loss,
            "Loss/state_loss": state_loss,
            "Loss/continue_loss": continue_loss,
            "State/kl": kl,
            "State/post_entropy": _normal_entropy(jax.lax.stop_gradient(post_std)),
            "State/prior_entropy": _normal_entropy(jax.lax.stop_gradient(prior_std)),
        }
        return loss, (zs, hs, metrics)

    def actor_loss_fn(actor_params, params, zs, hs, key):
        wm = params["world_model"]
        z0 = jax.lax.stop_gradient(zs).reshape(-1, agent.stochastic_size)
        h0 = jax.lax.stop_gradient(hs).reshape(-1, agent.recurrent_state_size)
        latents, _ = agent.imagination_scan(wm, actor_params, z0, h0, key, horizon)
        predicted_values = agent.critic.apply({"params": params["critic"]}, latents)
        predicted_rewards = agent.reward_model.apply({"params": wm["reward_model"]}, latents)
        if use_continues:
            cont_logits = agent.continue_model.apply({"params": wm["continue_model"]}, latents)
            continues = jax.nn.sigmoid(cont_logits)
        else:
            continues = jnp.ones_like(jax.lax.stop_gradient(predicted_rewards)) * gamma
        lambda_values = compute_lambda_values(
            predicted_rewards, predicted_values, continues, horizon, lmbda
        )
        discount = jax.lax.stop_gradient(
            jnp.cumprod(
                jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], axis=0), axis=0
            )
        )
        policy_loss = -jnp.mean(discount * lambda_values)
        # learn-stats aux (scalars only): imagined-value statistics + the raw
        # lambda-vs-baseline TD error (dv1's actor has no entropy term)
        aux_stats = learn_stats.maybe(learn_on, lambda: {
            **learn_stats.value_stats(jax.lax.stop_gradient(predicted_values)),
            **learn_stats.td_quantiles(
                jax.lax.stop_gradient(lambda_values - predicted_values[: lambda_values.shape[0]])
            ),
        })
        return policy_loss, (latents, lambda_values, discount, aux_stats)

    def critic_loss_fn(critic_params, latents, lambda_values, discount):
        pred = agent.critic.apply({"params": critic_params}, latents[:-1])
        lp = _normal1_logprob(pred, jax.lax.stop_gradient(lambda_values), 1)
        return -jnp.mean(discount[..., 0] * lp)

    # donate_argnums: XLA reuses the train-state buffers in place instead of
    # copying them every gradient step (drivers always rebind to the returned
    # trees, so the invalidated inputs are never read again).
    # state_shardings (parallel/sharding.py build_state_shardings) pins the
    # state outputs' mesh placement so GSPMD cannot reshard them on output.
    jit_kwargs = {"out_shardings": tuple(state_shardings)} if state_shardings is not None else {}

    @partial(jax.jit, donate_argnums=(0, 1), **jit_kwargs)
    def train_step(params, opt_state, batch, k):
        k_world, k_img = jax.random.split(jnp.asarray(k))

        (w_loss, (zs, hs, w_metrics)), w_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(
            params["world_model"], batch, k_world
        )
        w_updates, new_wopt = world_tx.update(w_grads, opt_state["world_model"], params["world_model"])
        params = {**params, "world_model": optax.apply_updates(params["world_model"], w_updates)}
        opt_state = {**opt_state, "world_model": new_wopt}

        (a_loss, (latents, lambda_values, discount, aux_stats)), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(params["actor"], params, zs, hs, k_img)
        a_updates, new_aopt = actor_tx.update(a_grads, opt_state["actor"], params["actor"])
        params = {**params, "actor": optax.apply_updates(params["actor"], a_updates)}
        opt_state = {**opt_state, "actor": new_aopt}

        latents_sg = jax.lax.stop_gradient(latents)
        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic"], latents_sg, lambda_values, discount
        )
        c_updates, new_copt = critic_tx.update(c_grads, opt_state["critic"], params["critic"])
        params = {**params, "critic": optax.apply_updates(params["critic"], c_updates)}
        opt_state = {**opt_state, "critic": new_copt}

        metrics = dict(w_metrics)
        metrics["Loss/policy_loss"] = a_loss
        metrics["Loss/value_loss"] = c_loss
        metrics["Grads/world_model"] = optax.global_norm(w_grads)
        metrics["Grads/actor"] = optax.global_norm(a_grads)
        metrics["Grads/critic"] = optax.global_norm(c_grads)
        # training-health block, riding the metrics dict (Learn/ prefix —
        # utils/learn_stats.py; extracted by RunTelemetry.observe_learn)
        if learn_on:
            metrics.update(aux_stats)
            metrics.update(learn_stats.group_stats(
                "world_model", grads=w_grads, updates=w_updates,
                params=params["world_model"], opt_state=new_wopt, clip=clips["world_model"],
            ))
            metrics.update(learn_stats.group_stats(
                "actor", grads=a_grads, updates=a_updates,
                params=params["actor"], opt_state=new_aopt, clip=clips["actor"],
            ))
            metrics.update(learn_stats.group_stats(
                "critic", grads=c_grads, updates=c_updates,
                params=params["critic"], opt_state=new_copt, clip=clips["critic"],
            ))
            metrics.update(learn_stats.kl_stats(
                w_metrics["State/kl"],
                w_metrics["State/post_entropy"],
                w_metrics["State/prior_entropy"],
            ))
            metrics["Learn/loss/world_model"] = w_loss
            metrics["Learn/loss/actor"] = a_loss
            metrics["Learn/loss/critic"] = c_loss
        return params, opt_state, metrics

    def train_phase(params, opt_state, data, train_key):
        return foreach_gradient_step(train_step, (params, opt_state), data, train_key)

    # the compiled unit, exposed for FLOPs/MFU accounting (utils/mfu.py, obs/)
    train_phase.train_step = train_step
    return train_phase


@register_fused_program(
    "dreamer_v1.train_step",
    min_donated=2,
    doc="fused single-gradient-step Dreamer-V1 world/actor/critic update",
)
def _aot_train_step():
    """Tiny DV1 agent through the loop's own factory (optimizer construction is
    identical across the dreamer family — shared via dv3's build_optimizers)."""
    from sheeprl_tpu.algos.dreamer_v1.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_optimizers
    from sheeprl_tpu.analysis.programs import (
        tiny_dreamer_batch,
        tiny_dreamer_cfg,
        tiny_fabric,
        tiny_obs_space,
    )

    cfg = tiny_dreamer_cfg("dreamer_v1")
    fabric = tiny_fabric()
    agent, params = build_agent(fabric, (4,), False, cfg, tiny_obs_space(), jax.random.PRNGKey(0))
    world_tx, actor_tx, critic_tx, opt_state = build_optimizers(cfg, params)
    train_phase = make_train_phase(agent, cfg, world_tx, actor_tx, critic_tx)
    batch = tiny_dreamer_batch(cfg)
    args = (params, opt_state, batch, np.asarray(jax.random.PRNGKey(1)))
    return train_phase.train_step, args


@register_algorithm()
def main(fabric, cfg: Dict[str, Any], exploration_actor_params=None):
    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    cfg.env.frame_stack = 1

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    num_envs = int(cfg.env.num_envs)
    envs = vectorized_env(
        [
            partial(
                RestartOnException,
                make_env(
                    cfg,
                    cfg.seed + rank * num_envs + i,
                    rank * num_envs,
                    log_dir if rank == 0 else None,
                    "train",
                    vector_env_idx=i,
                ),
            )
            for i in range(num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        agent_key,
        state["agent"] if state else None,
    )
    player = PlayerDV1(agent, num_envs, cnn_keys, mlp_keys)

    def _tx(opt_cfg, clip):
        base = instantiate(opt_cfg)
        if clip is not None and clip > 0:
            return optax.chain(optax.clip_by_global_norm(clip), base)
        return base

    world_tx = _tx(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = _tx(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = _tx(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    opt_state = {
        "world_model": world_tx.init(params["world_model"]),
        "actor": actor_tx.init(params["actor"]),
        "critic": critic_tx.init(params["critic"]),
    }
    if state is not None and "opt_state" in state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // int(num_envs * world_size) if not cfg.dry_run else 8
    rb = EnvIndependentReplayBuffer(
        buffer_size,
        n_envs=num_envs,
        obs_keys=tuple(obs_keys),
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        buffer_cls=SequentialReplayBuffer,
    )
    if state is not None and "rb" in state:
        rb = state["rb"]

    from sheeprl_tpu.parallel.sharding import build_state_shardings

    train_phase = make_train_phase(
        agent, cfg, world_tx, actor_tx, critic_tx,
        state_shardings=build_state_shardings(fabric, params, opt_state),
    )

    act = ActPlacement(fabric, lambda p: {"world_model": p["world_model"], "actor": p["actor"]})
    act_params = act.view(params)
    key = act.place(key)
    if exploration_actor_params is not None:
        exploration_actor_params = act.place(exploration_actor_params)

    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * num_envs if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(num_envs * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state is not None and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    # replay hot path: async prefetcher (sampling + sharded staging off-thread) or the
    # exact inline path when buffer.prefetch.enabled=false. Built AFTER the resume
    # block above so a restored batch size shapes the staged units.
    sampler = make_replay_sampler(
        rb,
        cfg.buffer.get("prefetch"),
        sample_kwargs=dict(
            batch_size=cfg.algo.per_rank_batch_size * world_size,
            sequence_length=cfg.algo.per_rank_sequence_length,
        ),
        uint8_keys=cnn_keys,
        sharding=fabric.sharding(None, None, "data") if fabric.num_devices > 1 else None,
        name="dv1-replay-prefetch",
    )
    telemetry.attach_sampler(sampler)

    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    expl_cfg = agent.actor_cfg

    def expl_amount(step: int) -> float:
        amount = expl_cfg["expl_amount"]
        if expl_cfg["expl_decay"]:
            amount = amount * (0.5 ** (step / expl_cfg["expl_decay"]))
        return max(amount, expl_cfg["expl_min"])

    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states(act_params)

    cumulative_per_rank_gradient_steps = 0
    train_step = 0
    last_train = 0
    act_dim = int(np.sum(actions_dim))

    bench = BenchWindow()

    for iter_num in range(start_iter, total_iters + 1):
        bench.maybe_start(policy_step, params)
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and state is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    per_dim = actions.reshape(num_envs, len(actions_dim)).T
                    actions = np.concatenate(
                        [np.eye(dim, dtype=np.float32)[act] for act, dim in zip(per_dim, actions_dim)],
                        axis=-1,
                    )
            else:
                jobs = prepare_obs(fabric, obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
                actions, key = player.get_actions(
                    # p2e finetuning acts with the exploration actor during the
                    # prefill, then switches to the (trained) task actor
                    {**act_params, "actor": exploration_actor_params}
                    if exploration_actor_params is not None and iter_num <= learning_starts
                    else act_params,
                    jobs,
                    key,
                    expl_amount=expl_amount(policy_step),
                )
                actions = np.asarray(actions)
                if is_continuous:
                    real_actions = actions
                else:
                    splits = np.cumsum(actions_dim)[:-1]
                    real_actions = np.stack(
                        [b.argmax(-1) for b in np.split(actions, splits, axis=-1)], axis=-1
                    )

            step_data["actions"] = actions.reshape((1, num_envs, -1)).astype(np.float32)
            sampler.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])
        if "restart_on_exception" in infos:
            # surface the RestartOnException crash-restart (previously invisible)
            telemetry.observe_env_restart(int(np.sum(infos["restart_on_exception"])))

        ep_info = infos.get("final_info", infos)
        if (cfg.metric.log_level > 0 or telemetry.enabled) and "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
        final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
        if final_obs_arr is not None:
            for idx in range(num_envs):
                if final_obs_arr[idx] is not None:
                    for k in obs_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])

        for k in obs_keys:
            step_data[k] = np.asarray(next_obs[k])[np.newaxis]
        obs = next_obs

        rewards = np.asarray(rewards, dtype=np.float32).reshape((1, num_envs, -1))
        step_data["terminated"] = np.asarray(terminated, np.float32).reshape((1, num_envs, -1))
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape((1, num_envs, -1))
        step_data["rewards"] = clip_rewards_fn(rewards)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (real_next_obs[k][dones_idxes])[np.newaxis]
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, reset_envs, act_dim), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            sampler.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            step_data["rewards"][:, dones_idxes] = 0.0
            step_data["terminated"][:, dones_idxes] = 0.0
            step_data["truncated"][:, dones_idxes] = 0.0
            step_data["is_first"][:, dones_idxes] = 1.0
            player.init_states(act_params, dones_idxes)

        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(per_rank_gradient_steps)
                    key, train_key = jax.random.split(key)
                    # one-shot injected learning pathology (resilience.fault=
                    # lr_spike): identity unless armed this iteration
                    params = apply_armed_learn_fault(params)
                    params, opt_state, metrics = train_phase(
                        params, opt_state, data, np.asarray(train_key)
                    )
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                    act_params = act.view(params)
                    telemetry.observe_train(per_rank_gradient_steps, metrics)
                    telemetry.observe_learn(metrics)
                    if telemetry.wants_program("train_step"):
                        batch_avals = unit_avals(data)
                        telemetry.register_program(
                            "train_step",
                            train_phase.train_step,
                            (params, opt_state, batch_avals, jnp.asarray(train_key)),
                            units=1,
                        )
                    if aggregator and not aggregator.disabled:
                        for mk, mv in metrics.items():
                            aggregator.update(mk, float(np.asarray(mv)))

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step
            last_train = train_step

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            # quiesce the prefetch worker so the pickled buffer (incl. its RNG
            # state) is not a torn mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    bench.finish(policy_step, params)

    sampler.close()
    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(player, act_params, fabric, cfg, log_dir, greedy=False)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
