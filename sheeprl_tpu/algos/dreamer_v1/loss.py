"""Dreamer-V1 losses (reference sheeprl/algos/dreamer_v1/loss.py:9-97)."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v1.agent import normal_kl


def reconstruction_loss(
    observation_log_probs: Dict[str, jax.Array],
    reward_log_prob: jax.Array,
    posterior_mean: jax.Array,
    posterior_std: jax.Array,
    prior_mean: jax.Array,
    prior_std: jax.Array,
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
    continue_log_prob: Optional[jax.Array] = None,
    continue_scale_factor: float = 10.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (loss, kl, state_loss, reward_loss, observation_loss, continue_loss)."""
    observation_loss = -sum(lp.mean() for lp in observation_log_probs.values())
    reward_loss = -reward_log_prob.mean()
    kl = normal_kl(posterior_mean, posterior_std, prior_mean, prior_std).mean()
    state_loss = jnp.maximum(kl, kl_free_nats)
    if continue_log_prob is not None:
        continue_loss = continue_scale_factor * -continue_log_prob.mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    loss = kl_regularizer * state_loss + observation_loss + reward_loss + continue_loss
    return loss, kl, state_loss, reward_loss, observation_loss, continue_loss
