"""Dreamer-V1 support (reference: sheeprl/algos/dreamer_v1/utils.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.utils import prepare_obs, test  # noqa: F401 — shared

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    horizon: int,
    lmbda: float = 0.95,
) -> jax.Array:
    """DV1 lambda-return recursion (reference dreamer_v1/utils.py:42-77): produces
    ``horizon - 1`` targets; the final step bootstraps with the *full* last value
    (not scaled by 1 - lambda).

    Accumulates in float32 regardless of compute precision (see the shared
    compute_lambda_values note in utils/utils.py): mixed bf16/fp32 inputs would
    otherwise break the scan carry-type invariant."""
    rewards = rewards.astype(jnp.float32)
    values = values.astype(jnp.float32)
    continues = continues.astype(jnp.float32)
    # entries t = 0..H-2: t < H-2 uses values[t+1] * (1 - lambda), t == H-2 uses
    # values[H-1] unscaled
    next_values = jnp.concatenate([values[1:-1] * (1 - lmbda), values[-1:]], axis=0)
    deltas = rewards[: horizon - 1] + next_values * continues[: horizon - 1]

    def step(agg, inp):
        delta_t, cont_t = inp
        agg = delta_t + lmbda * cont_t * agg
        return agg, agg

    init = jnp.zeros_like(values[0])
    _, lv_rev = jax.lax.scan(step, init, (deltas[::-1], continues[: horizon - 1][::-1]))
    return lv_rev[::-1]
