"""Dreamer-V1 agent, Flax/JAX-native.

Capability parity with the reference (sheeprl/algos/dreamer_v1/agent.py:
RecurrentModel:31, RSSM:64, PlayerDV1:219, build_agent:329): continuous-latent
(Gaussian) RSSM — representation/transition emit (mean, raw-std) chunks, std is
softplus + min_std — reusing the Dreamer-V2 encoder/decoder/actor modules (the
reference does the same, dreamer_v1/agent.py:16-19)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v2.agent import (
    Actor,
    CNNDecoder,
    CNNEncoder,
    Decoder,
    DenseStack,
    Encoder,
    MLPDecoder,
    MLPEncoder,
    MLPHead,
    RecurrentModel,
    actor_logprob_entropy,  # noqa: F401 — shared policy math
    actor_sample,
    add_exploration_noise,
)


def gaussian_state(
    mean_std: jax.Array, min_std: float, key: Optional[jax.Array] = None, sample: bool = True
) -> Tuple[Tuple[jax.Array, jax.Array], jax.Array]:
    """(mean, std), state — reparameterized Normal sample (reference
    dreamer_v1/utils.py:80-103)."""
    mean, std_raw = jnp.split(mean_std, 2, axis=-1)
    std = jax.nn.softplus(std_raw) + min_std
    if sample:
        state = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
    else:
        state = mean
    return (mean, std), state


def normal_kl(mean_p, std_p, mean_q, std_q) -> jax.Array:
    """KL( N(p) || N(q) ) summed over the last axis (Independent event dim)."""
    kl = (
        jnp.log(std_q / std_p)
        + (jnp.square(std_p) + jnp.square(mean_p - mean_q)) / (2 * jnp.square(std_q))
        - 0.5
    )
    return kl.sum(axis=-1)


@dataclass
class DV1Agent:
    """Params layout matches DV2Agent, with Gaussian stochastic states."""

    encoder: Encoder
    recurrent_model: RecurrentModel
    representation_model: MLPHead
    transition_model: MLPHead
    observation_model: Decoder
    reward_model: MLPHead
    continue_model: Optional[MLPHead]
    actor: Actor
    critic: MLPHead
    actions_dim: Sequence[int]
    is_continuous: bool
    stochastic_size: int
    recurrent_state_size: int
    min_std: float = 0.1
    actor_cfg: Dict[str, Any] = field(default_factory=dict)

    # kept for API symmetry with DV2/DV3 players
    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size

    @property
    def discrete_size(self) -> int:
        return 1

    @property
    def latent_state_size(self) -> int:
        return self.stochastic_size + self.recurrent_state_size

    def _representation(self, wm, h, embedded, key, sample=True):
        out = self.representation_model.apply(
            {"params": wm["representation_model"]}, jnp.concatenate([h, embedded], axis=-1)
        )
        return gaussian_state(out, self.min_std, key, sample)

    def _transition(self, wm, h, key, sample=True):
        out = self.transition_model.apply({"params": wm["transition_model"]}, h)
        return gaussian_state(out, self.min_std, key, sample)

    def _recurrent(self, wm, z, a, h):
        return self.recurrent_model.apply(
            {"params": wm["recurrent_model"]}, jnp.concatenate([z, a], axis=-1), h
        )

    def dynamic_scan(self, wm, embedded, actions, key):
        """Posterior/prior unroll (reference RSSM.dynamic:97-134 — no is_first
        masking in Dreamer-V1). Returns (hs, zs, post_mean, post_std, prior_mean,
        prior_std), all time-major."""
        T, B = embedded.shape[:2]
        keys = jax.random.split(key, T)

        def step(carry, inp):
            h, z = carry
            a, e, k = inp
            h = self._recurrent(wm, z, a, h)
            (prior_mean, prior_std), _ = self._transition(wm, h, jax.random.fold_in(k, 0))
            (post_mean, post_std), z = self._representation(wm, h, e, k)
            return (h, z), (h, z, post_mean, post_std, prior_mean, prior_std)

        init = (
            jnp.zeros((B, self.recurrent_state_size), embedded.dtype),
            jnp.zeros((B, self.stochastic_size), embedded.dtype),
        )
        _, outs = jax.lax.scan(step, init, (actions, embedded, keys))
        return outs

    def imagination_scan(self, wm, actor_params, z0, h0, key, horizon):
        """DV1 imagination (reference dreamer_v1.py:243-250): actor acts, dynamics
        step; the trajectory collects the H *imagined* states (and the actions that
        produced them — the p2e intrinsic reward consumes those)."""

        def step(carry, k):
            z, h, latent = carry
            pre = self.actor.apply({"params": actor_params}, jax.lax.stop_gradient(latent))
            a = actor_sample(self, pre, jax.random.fold_in(k, 1))
            h = self._recurrent(wm, z, a, h)
            _, z = self._transition(wm, h, k)
            latent = jnp.concatenate([z, h], axis=-1)
            return (z, h, latent), (latent, a)

        latent0 = jnp.concatenate([z0, h0], axis=-1)
        keys = jax.random.split(key, horizon)
        _, (latents, actions) = jax.lax.scan(step, (z0, h0, latent0), keys)
        return latents, actions


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV1Agent, Dict[str, Any]]:
    wm_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    dtype = fabric.compute_dtype

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    layer_norm = cfg.algo.get("layer_norm", False)

    cnn_encoder = (
        CNNEncoder(
            keys=cnn_keys,
            channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
            activation=cfg.algo.cnn_act,
            layer_norm=layer_norm,
            dtype=dtype,
        )
        if cnn_keys
        else None
    )
    mlp_encoder = (
        MLPEncoder(
            keys=mlp_keys,
            mlp_layers=wm_cfg.encoder.mlp_layers,
            dense_units=wm_cfg.encoder.dense_units,
            activation=cfg.algo.dense_act,
            layer_norm=layer_norm,
            dtype=dtype,
        )
        if mlp_keys
        else None
    )
    encoder = Encoder(cnn_encoder, mlp_encoder)

    stochastic_size = wm_cfg.stochastic_size
    recurrent_state_size = wm_cfg.recurrent_model.recurrent_state_size
    latent_state_size = stochastic_size + recurrent_state_size

    recurrent_model = RecurrentModel(
        recurrent_state_size=recurrent_state_size,
        dense_units=wm_cfg.recurrent_model.dense_units,
        activation=cfg.algo.dense_act,
        layer_norm=False,
        dtype=dtype,
    )
    representation_model = MLPHead(
        units=wm_cfg.representation_model.hidden_size,
        n_layers=1,
        output_dim=stochastic_size * 2,
        activation=wm_cfg.representation_model.dense_act,
        layer_norm=layer_norm,
        dtype=dtype,
    )
    transition_model = MLPHead(
        units=wm_cfg.transition_model.hidden_size,
        n_layers=1,
        output_dim=stochastic_size * 2,
        activation=wm_cfg.transition_model.dense_act,
        layer_norm=layer_norm,
        dtype=dtype,
    )

    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    keys = jax.random.split(key, 10)
    enc_vars = encoder.init(keys[0], dummy_obs)
    embedded = encoder.apply(enc_vars, dummy_obs)
    cnn_encoder_output_dim = (
        int(np.asarray(cnn_encoder.apply({"params": enc_vars["params"]["cnn_encoder"]}, dummy_obs)).shape[-1])
        if cnn_encoder is not None
        else 0
    )

    cnn_decoder = (
        CNNDecoder(
            keys=cnn_dec_keys,
            output_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_dec_keys],
            channels_multiplier=wm_cfg.observation_model.cnn_channels_multiplier,
            cnn_encoder_output_dim=cnn_encoder_output_dim,
            activation=cfg.algo.cnn_act,
            layer_norm=layer_norm,
            dtype=dtype,
        )
        if cnn_dec_keys
        else None
    )
    mlp_decoder = (
        MLPDecoder(
            keys=mlp_dec_keys,
            output_dims=[obs_space[k].shape[0] for k in mlp_dec_keys],
            mlp_layers=wm_cfg.observation_model.mlp_layers,
            dense_units=wm_cfg.observation_model.dense_units,
            activation=cfg.algo.dense_act,
            layer_norm=layer_norm,
            dtype=dtype,
        )
        if mlp_dec_keys
        else None
    )
    observation_model = Decoder(cnn_decoder, mlp_decoder)
    reward_model = MLPHead(
        units=wm_cfg.reward_model.dense_units,
        n_layers=wm_cfg.reward_model.mlp_layers,
        output_dim=1,
        activation=cfg.algo.dense_act,
        layer_norm=layer_norm,
        dtype=dtype,
    )
    continue_model = (
        MLPHead(
            units=wm_cfg.discount_model.dense_units,
            n_layers=wm_cfg.discount_model.mlp_layers,
            output_dim=1,
            activation=cfg.algo.dense_act,
            layer_norm=layer_norm,
            dtype=dtype,
        )
        if wm_cfg.use_continues
        else None
    )
    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=actor_cfg.dense_units,
        mlp_layers=actor_cfg.mlp_layers,
        activation=actor_cfg.dense_act,
        layer_norm=layer_norm,
        dtype=dtype,
    )
    critic = MLPHead(
        units=critic_cfg.dense_units,
        n_layers=critic_cfg.mlp_layers,
        output_dim=1,
        activation=critic_cfg.dense_act,
        layer_norm=layer_norm,
        dtype=dtype,
    )

    agent = DV1Agent(
        encoder=encoder,
        recurrent_model=recurrent_model,
        representation_model=representation_model,
        transition_model=transition_model,
        observation_model=observation_model,
        reward_model=reward_model,
        continue_model=continue_model,
        actor=actor,
        critic=critic,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        stochastic_size=stochastic_size,
        recurrent_state_size=recurrent_state_size,
        min_std=wm_cfg.min_std,
        actor_cfg={
            "init_std": actor_cfg.init_std,
            "min_std": actor_cfg.min_std,
            "expl_amount": actor_cfg.get("expl_amount", 0.0),
            "expl_decay": actor_cfg.get("expl_decay", 0.0),
            "expl_min": actor_cfg.get("expl_min", 0.0),
        },
    )

    act_dim = int(np.sum(actions_dim))
    h = jnp.zeros((1, recurrent_state_size), jnp.float32)
    z = jnp.zeros((1, stochastic_size), jnp.float32)
    latent = jnp.zeros((1, latent_state_size), jnp.float32)
    wm_params = {
        "encoder": enc_vars["params"],
        "recurrent_model": recurrent_model.init(
            keys[1], jnp.concatenate([z, jnp.zeros((1, act_dim), jnp.float32)], axis=-1), h
        )["params"],
        "representation_model": representation_model.init(
            keys[2], jnp.concatenate([h, embedded], axis=-1)
        )["params"],
        "transition_model": transition_model.init(keys[3], h)["params"],
        "observation_model": observation_model.init(keys[4], latent)["params"],
        "reward_model": reward_model.init(keys[5], latent)["params"],
    }
    if continue_model is not None:
        wm_params["continue_model"] = continue_model.init(keys[6], latent)["params"]
    params = {
        "world_model": wm_params,
        "actor": actor.init(keys[7], latent)["params"],
        "critic": critic.init(keys[8], latent)["params"],
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    if getattr(fabric, "model_parallel", False):
        # data x model mesh: land every kernel in its rule-derived model-axis
        # shard (parallel/sharding.py); a 1-D mesh leaves this a no-op
        params = fabric.shard_params(params)
    return agent, params


class PlayerDV1:
    """Stateful env-interaction wrapper (reference PlayerDV1, agent.py:219-328)."""

    def __init__(self, agent: DV1Agent, num_envs: int, cnn_keys: Sequence[str], mlp_keys: Sequence[str]):
        self.agent = agent
        self.num_envs = num_envs
        self.cnn_keys = tuple(cnn_keys)
        self.mlp_keys = tuple(mlp_keys)
        self.actions: Optional[jax.Array] = None
        self.recurrent_state: Optional[jax.Array] = None
        self.stochastic_state: Optional[jax.Array] = None

        agent_ref = self.agent

        def _step(params, obs, a, h, z, key, greedy: bool, expl_amount):
            wm = params["world_model"]
            embedded = agent_ref.encoder.apply({"params": wm["encoder"]}, obs)
            h = agent_ref._recurrent(wm, z, a, h)
            # chain key advanced in-program (saves ~0.5 ms/step of host dispatch)
            key, k_repr, k_act, k_expl = jax.random.split(key, 4)
            _, z = agent_ref._representation(wm, h, embedded, k_repr)
            latent = jnp.concatenate([z, h], axis=-1)
            pre = agent_ref.actor.apply({"params": params["actor"]}, latent)
            actions = actor_sample(agent_ref, pre, k_act, greedy=greedy)
            actions = add_exploration_noise(agent_ref, actions, k_expl, expl_amount)
            return actions, h, z, key

        self._step = jax.jit(_step, static_argnames=("greedy",))

    def init_states(self, params: Dict = None, reset_envs: Optional[Sequence[int]] = None) -> None:
        act_dim = int(np.sum(self.agent.actions_dim))
        if reset_envs is None or len(reset_envs) == 0:
            self.actions = jnp.zeros((self.num_envs, act_dim), jnp.float32)
            self.recurrent_state = jnp.zeros((self.num_envs, self.agent.recurrent_state_size), jnp.float32)
            self.stochastic_state = jnp.zeros((self.num_envs, self.agent.stochastic_size), jnp.float32)
        else:
            idx = np.asarray(reset_envs)
            self.actions = self.actions.at[idx].set(0.0)
            self.recurrent_state = self.recurrent_state.at[idx].set(0.0)
            self.stochastic_state = self.stochastic_state.at[idx].set(0.0)

    def get_actions(
        self, params: Dict, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False, expl_amount: float = 0.0
    ):
        """Returns ``(actions, key)`` — the advanced PRNG chain key."""
        actions, self.recurrent_state, self.stochastic_state, key = self._step(
            params, obs, self.actions, self.recurrent_state, self.stochastic_state, key, greedy,
            jnp.asarray(expl_amount, jnp.float32),
        )
        self.actions = actions
        return actions, key
