"""Dreamer-V1 serving extractor: the shared Dreamer serving shape
(``dreamer_v3/serve.py``) with DV1's zero initial carry and its Gaussian RSSM
stochastic state."""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.serve import dreamer_serve_policy
from sheeprl_tpu.serve.policy import ServePolicy
from sheeprl_tpu.utils.registry import register_serve_policy


@register_serve_policy(algorithms=["dreamer_v1"])
def get_serve_policy(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> ServePolicy:
    from sheeprl_tpu.algos.dreamer_v2.agent import actor_sample
    from sheeprl_tpu.algos.dreamer_v1.agent import build_agent

    def init_carry(agent, wm_params):
        # PlayerDV1 resets to zeros; DV1's stochastic state is Gaussian (flat
        # stochastic_size, no discrete factor)
        return (
            jnp.zeros((agent.recurrent_state_size,), jnp.float32),
            jnp.zeros((agent.stochastic_size,), jnp.float32),
        )

    return dreamer_serve_policy(
        fabric,
        cfg,
        state,
        build_agent=build_agent,
        actor_sample=actor_sample,
        init_carry=init_carry,
        family="dreamer_v1",
    )
