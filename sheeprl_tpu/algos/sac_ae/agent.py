"""SAC-AE agent, Flax/JAX-native (pixel SAC with an autoencoder, arXiv:1910.01741).

Capability parity with the reference (sheeprl/algos/sac_ae/agent.py: CNNEncoder:26,
MLPEncoder:91, MLPDecoder:122, CNNDecoder:155, SACAEQFunction:207, SACAECritic:225,
SACAEContinuousActor:239, SACAEAgent:323, build_agent:430):

- one shared conv trunk feeds both actor and critic; each side owns its projection
  head (the reference ties ``.model`` between two encoder instances — here the
  sharing is explicit in the params pytree: ``conv`` + ``mlp_enc`` are shared,
  ``critic_cnn_fc`` / ``actor_cnn_fc`` are per-side);
- "detach encoder features" becomes ``stop_gradient`` on the trunk outputs in the
  actor path;
- the twin critics are a vmapped ensemble (stacked params, one apply);
- the decoder reconstructs all obs keys from the critic-side features.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import prod
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import CriticEnsemble
from sheeprl_tpu.models.models import MLP
from sheeprl_tpu.ops.deconv import FusedConvTransposeS2Valid

LOG_STD_MAX = 2.0
LOG_STD_MIN = -10.0


class ConvTrunk(nn.Module):
    """The SAC-AE conv stack: 4 k3 convs (stride 2,1,1,1), ReLU, flattened output."""

    keys: Sequence[str]
    channels_multiplier: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        lead = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:])
        x = jnp.moveaxis(x, -3, -1).astype(self.dtype)  # NCHW -> NHWC
        for stride in (2, 1, 1, 1):
            x = nn.Conv(32 * self.channels_multiplier, (3, 3), strides=(stride, stride), padding="VALID", dtype=self.dtype)(x)
            x = jax.nn.relu(x)
        return x.reshape(*lead, -1)


class EncoderFC(nn.Module):
    """Per-side projection: Dense → LayerNorm → tanh (reference CNNEncoder.fc)."""

    features_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(self.features_dim, dtype=self.dtype)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return jnp.tanh(x)


class VectorEncoder(nn.Module):
    keys: Sequence[str]
    dense_units: int
    mlp_layers: int
    dense_act: Any = "relu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        return MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(x)


class CNNDecoderAE(nn.Module):
    """features → fc → conv-shape → 3 k3 s1 deconvs → k4 s2 deconv to screen_size
    (reference CNNDecoder:155-204; the final stage is k4 s2 VALID, the shape-exact
    inverse of the k3 s2 encoder stage without torch's output_padding trick)."""

    keys: Sequence[str]
    output_channels: Sequence[int]
    conv_shape: Tuple[int, int, int]  # (H, W, C) of the encoder trunk output
    channels_multiplier: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, feat: jax.Array) -> Dict[str, jax.Array]:
        lead = feat.shape[:-1]
        x = nn.Dense(int(np.prod(self.conv_shape)), dtype=self.dtype)(feat)
        x = x.reshape(-1, *self.conv_shape)
        for _ in range(3):
            x = nn.ConvTranspose(32 * self.channels_multiplier, (3, 3), strides=(1, 1), padding="VALID", dtype=self.dtype)(x)
            x = jax.nn.relu(x)
        # phase-decomposed drop-in for the stride-2 upsample (ops/deconv.py); the
        # explicit name keeps nn.ConvTranspose's auto-name slot (checkpoints intact)
        x = FusedConvTransposeS2Valid(
            sum(self.output_channels), kernel_size=4, dtype=self.dtype, name="ConvTranspose_3"
        )(x)
        x = jnp.moveaxis(x, -1, -3)  # NHWC -> NCHW
        x = x.reshape(*lead, *x.shape[-3:])
        splits = np.cumsum(self.output_channels)[:-1].tolist()
        return {k: v for k, v in zip(self.keys, jnp.split(x, splits, axis=-3))}


class MLPDecoderAE(nn.Module):
    keys: Sequence[str]
    output_dims: Sequence[int]
    dense_units: int
    mlp_layers: int
    dense_act: Any = "relu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, feat: jax.Array) -> Dict[str, jax.Array]:
        x = MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
            dtype=self.dtype,
        )(feat)
        return {
            k: nn.Dense(dim, dtype=self.dtype)(x) for k, dim in zip(self.keys, self.output_dims)
        }


class SACAEActorHead(nn.Module):
    """MLP(hidden, hidden) → mean / tanh-bounded log-std heads (reference
    SACAEContinuousActor:239-284)."""

    action_dim: int
    hidden_size: int = 1024
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, feat: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu", dtype=self.dtype)(feat)
        mean = nn.Dense(self.action_dim, dtype=self.dtype)(x)
        log_std = nn.Dense(self.action_dim, dtype=self.dtype)(x)
        log_std = jnp.tanh(log_std)
        log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (log_std + 1)
        return mean, jnp.exp(log_std)


@dataclass
class SACAEAgent:
    """Module container + pure feature functions. Params layout:
    {"conv", "mlp_enc", "critic_cnn_fc", "actor_cnn_fc", "qfs", "actor",
    "log_alpha", "decoder": {"cnn", "mlp"},
    "target": {"conv", "mlp_enc", "critic_cnn_fc", "qfs"}}."""

    conv: Optional[ConvTrunk]
    mlp_enc: Optional[VectorEncoder]
    cnn_fc: Optional[EncoderFC]
    qfs: CriticEnsemble
    actor: SACAEActorHead
    cnn_decoder: Optional[CNNDecoderAE]
    mlp_decoder: Optional[MLPDecoderAE]
    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    action_scale: Any = 1.0
    action_bias: Any = 0.0

    def features(
        self,
        params: Dict,
        obs: Dict[str, jax.Array],
        side: str = "critic",
        detach_encoder_features: bool = False,
        target: bool = False,
    ) -> jax.Array:
        """Concatenated encoder features. ``detach_encoder_features`` stops gradients
        at the shared trunks (the per-side cnn fc keeps training, mirroring the
        reference's detach point inside CNNEncoder.forward:77-87)."""
        src = params["target"] if target else params
        outs = []
        if self.conv is not None:
            conv_out = self.conv.apply({"params": src["conv"]}, obs)
            if detach_encoder_features:
                conv_out = jax.lax.stop_gradient(conv_out)
            fc_key = "critic_cnn_fc" if (side == "critic" or target) else "actor_cnn_fc"
            fc_params = src["critic_cnn_fc"] if target else params[fc_key]
            outs.append(self.cnn_fc.apply({"params": fc_params}, conv_out))
        if self.mlp_enc is not None:
            mlp_out = self.mlp_enc.apply({"params": src["mlp_enc"]}, obs)
            if detach_encoder_features:
                mlp_out = jax.lax.stop_gradient(mlp_out)
            outs.append(mlp_out)
        return jnp.concatenate(outs, axis=-1)

    def reconstruct(self, params: Dict, feat: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder.apply({"params": params["decoder"]["cnn"]}, feat))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder.apply({"params": params["decoder"]["mlp"]}, feat))
        return out


def build_agent(
    fabric,
    cfg,
    observation_space,
    action_space,
    key: jax.Array,
    state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACAEAgent, Dict[str, Any]]:
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    dtype = fabric.compute_dtype
    act_dim = int(prod(action_space.shape))
    cm = int(cfg.algo.cnn_channels_multiplier)
    screen = int(cfg.env.screen_size)

    conv = ConvTrunk(keys=cnn_keys, channels_multiplier=cm, dtype=dtype) if cnn_keys else None
    cnn_fc = EncoderFC(features_dim=cfg.algo.encoder.features_dim, dtype=dtype) if cnn_keys else None
    mlp_enc = (
        VectorEncoder(
            keys=mlp_keys,
            dense_units=cfg.algo.encoder.dense_units,
            mlp_layers=cfg.algo.encoder.mlp_layers,
            dense_act=cfg.algo.encoder.dense_act,
            layer_norm=cfg.algo.encoder.layer_norm,
            dtype=dtype,
        )
        if mlp_keys
        else None
    )
    qfs = CriticEnsemble(n=cfg.algo.critic.n, hidden_size=cfg.algo.hidden_size, dtype=dtype)
    actor = SACAEActorHead(action_dim=act_dim, hidden_size=cfg.algo.hidden_size, dtype=dtype)

    # encoder trunk output spatial shape: k3 s2 then 3× k3 s1 on screen×screen;
    # the decoder's k4-s2 final stage inverts this exactly only for even sizes
    if screen % 2 != 0:
        raise ValueError(f"SAC-AE requires an even env.screen_size, got {screen}")
    s = (screen - 3) // 2 + 1
    s = s - 2 * 3  # three stride-1 k3 convs each remove 2
    conv_shape = (s, s, 32 * cm)

    cnn_decoder = (
        CNNDecoderAE(
            keys=cnn_dec_keys,
            output_channels=[int(np.prod(observation_space[k].shape[:-2])) for k in cnn_dec_keys],
            conv_shape=conv_shape,
            channels_multiplier=cm,
            dtype=dtype,
        )
        if cnn_dec_keys
        else None
    )
    mlp_decoder = (
        MLPDecoderAE(
            keys=mlp_dec_keys,
            output_dims=[observation_space[k].shape[0] for k in mlp_dec_keys],
            dense_units=cfg.algo.decoder.dense_units,
            mlp_layers=cfg.algo.decoder.mlp_layers,
            dense_act=cfg.algo.decoder.dense_act,
            layer_norm=cfg.algo.decoder.layer_norm,
            dtype=dtype,
        )
        if mlp_dec_keys
        else None
    )

    agent = SACAEAgent(
        conv=conv,
        mlp_enc=mlp_enc,
        cnn_fc=cnn_fc,
        qfs=qfs,
        actor=actor,
        cnn_decoder=cnn_decoder,
        mlp_decoder=mlp_decoder,
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        action_scale=jnp.asarray((np.asarray(action_space.high) - np.asarray(action_space.low)) / 2.0, jnp.float32),
        action_bias=jnp.asarray((np.asarray(action_space.high) + np.asarray(action_space.low)) / 2.0, jnp.float32),
    )

    keys = jax.random.split(key, 8)
    dummy_obs = {}
    for k in cnn_keys:
        shape = observation_space[k].shape
        # frame-stack dims fold into channels (runtime prepare_obs does the same)
        dummy_obs[k] = jnp.zeros((1, int(np.prod(shape[:-2])), *shape[-2:]), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *observation_space[k].shape), jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), jnp.float32)

    params: Dict[str, Any] = {"log_alpha": jnp.log(jnp.asarray([cfg.algo.alpha.alpha], jnp.float32))}
    feat_parts = []
    if conv is not None:
        params["conv"] = conv.init(keys[0], dummy_obs)["params"]
        conv_out = conv.apply({"params": params["conv"]}, dummy_obs)
        params["critic_cnn_fc"] = cnn_fc.init(keys[1], conv_out)["params"]
        params["actor_cnn_fc"] = cnn_fc.init(keys[2], conv_out)["params"]
        feat_parts.append(cnn_fc.apply({"params": params["critic_cnn_fc"]}, conv_out))
    if mlp_enc is not None:
        params["mlp_enc"] = mlp_enc.init(keys[3], dummy_obs)["params"]
        feat_parts.append(mlp_enc.apply({"params": params["mlp_enc"]}, dummy_obs))
    feat = jnp.concatenate(feat_parts, axis=-1)
    params["qfs"] = qfs.init(keys[4], feat, dummy_act)["params"]
    params["actor"] = actor.init(keys[5], feat)["params"]
    params["decoder"] = {}
    if cnn_decoder is not None:
        params["decoder"]["cnn"] = cnn_decoder.init(keys[6], feat)["params"]
    if mlp_decoder is not None:
        params["decoder"]["mlp"] = mlp_decoder.init(keys[7], feat)["params"]
    params["target"] = {
        k: jax.tree_util.tree_map(jnp.copy, params[k])
        for k in ("conv", "mlp_enc", "critic_cnn_fc", "qfs")
        if k in params
    }
    if state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, state)
    return agent, params
