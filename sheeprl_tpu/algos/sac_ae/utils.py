"""SAC-AE helpers (reference: sheeprl/algos/sac_ae/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}
MODELS_TO_REGISTER = {"agent", "encoder", "decoder"}


def preprocess_obs(obs: jax.Array, key: jax.Array, bits: int = 8) -> jax.Array:
    """Bit-depth reduction + uniform dequantization noise, centered
    (reference utils.py:68-76, arXiv:1807.03039)."""
    bins = 2**bits
    if bits < 8:
        obs = jnp.floor(obs / 2 ** (8 - bits))
    obs = obs / bins
    obs = obs + jax.random.uniform(key, obs.shape, obs.dtype) / bins
    return obs - 0.5


def prepare_obs(
    fabric, obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), mlp_keys: Sequence[str] = (), num_envs: int = 1
) -> Dict[str, np.ndarray]:
    """Images → [N, C, H, W] in [0, 1]; vectors → [N, D] floats (reference
    prepare_obs: images are divided by 255 only). Host arrays — see the dreamer_v3
    prepare_obs note on device placement."""
    out: Dict[str, np.ndarray] = {}
    for k in cnn_keys:
        v = np.asarray(obs[k], dtype=np.float32)
        out[k] = v.reshape(num_envs, -1, *v.shape[-2:]) / 255.0
    for k in mlp_keys:
        v = np.asarray(obs[k], dtype=np.float32)
        out[k] = v.reshape(num_envs, -1)
    return out


def test(agent, params, fabric, cfg: Dict[str, Any], log_dir: str) -> None:
    """Greedy (tanh-mean) single-env rollout."""
    from sheeprl_tpu.algos.sac.agent import greedy_action
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    while not done:
        jobs = prepare_obs(
            fabric, obs, cnn_keys=cfg.algo.cnn_keys.encoder, mlp_keys=cfg.algo.mlp_keys.encoder, num_envs=1
        )
        feat = agent.features(params, jobs, side="actor")
        mean, _ = agent.actor.apply({"params": params["actor"]}, feat)
        actions = np.asarray(greedy_action(mean, agent.action_scale, agent.action_bias))
        obs, reward, terminated, truncated, _ = env.step(actions.reshape(env.action_space.shape))
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(np.asarray(reward))
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
