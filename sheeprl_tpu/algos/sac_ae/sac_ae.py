"""SAC-AE, coupled training (capability parity with sheeprl/algos/sac_ae/sac_ae.py:
35-502): pixel SAC with autoencoder reconstruction regularization.

TPU-native structure (same shape as the SAC module): the act path is a small jitted
sampler; each iteration's gradient steps run as ONE jitted program scanning the
``[G, B, ...]`` replay block — critic → (gated) target EMA → (gated) actor+alpha →
(gated) encoder/decoder reconstruction, with the update-frequency gates from the
reference (critic.per_rank_target_network_update_freq, actor.per_rank_update_freq,
decoder.per_rank_update_freq) applied per scanned step via ``lax.cond``-free masked
updates on the cumulative step counter."""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.sac.agent import squash_and_logprob
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac_ae.agent import build_agent
from sheeprl_tpu.algos.sac_ae.utils import prepare_obs, preprocess_obs, test
from sheeprl_tpu.analysis.programs import register_fused_program
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import ActPlacement, Ratio, save_configs


def _masked_update(tx, grads, opt_state, group, apply_flag):
    """Optimizer step that is a no-op (params and opt-state kept) when
    ``apply_flag`` is 0 — the jit-able form of the reference's modulo-gated
    update branches."""
    updates, new_opt = tx.update(grads, opt_state, group)
    new_params = optax.apply_updates(group, updates)
    pick = lambda n, o: jnp.where(apply_flag, n, o)
    return (
        jax.tree_util.tree_map(pick, new_params, group),
        jax.tree_util.tree_map(pick, new_opt, opt_state),
    )


def critic_group(p):
    return {k: p[k] for k in ("conv", "mlp_enc", "critic_cnn_fc", "qfs") if k in p}


def actor_group(p):
    return {k: p[k] for k in ("actor", "actor_cnn_fc") if k in p}


def encoder_group(p):
    return {k: p[k] for k in ("conv", "mlp_enc", "critic_cnn_fc") if k in p}


def build_optimizers(cfg) -> Dict[str, Any]:
    """The five SAC-AE optimizers (reference sac_ae.py:211-248) — shared by the
    loop and the AOT registry."""
    return {
        "actor": instantiate(cfg.algo.actor.optimizer),
        "critic": instantiate(cfg.algo.critic.optimizer),
        "alpha": instantiate(cfg.algo.alpha.optimizer),
        "encoder": instantiate(cfg.algo.encoder.optimizer),
        "decoder": instantiate(cfg.algo.decoder.optimizer),
    }


def init_opt_state(txs: Dict[str, Any], params) -> Dict[str, Any]:
    return {
        "critic": txs["critic"].init(critic_group(params)),
        "actor": txs["actor"].init(actor_group(params)),
        "alpha": txs["alpha"].init(params["log_alpha"]),
        "encoder": txs["encoder"].init(encoder_group(params)),
        "decoder": txs["decoder"].init(params["decoder"]),
    }


def make_train_phase(agent, cfg, txs, target_entropy, jit_kwargs=None):
    """Build the fused SAC-AE train program: a ``lax.scan`` over the ``[G, B,
    ...]`` replay block running critic -> targets EMA -> (gated) actor/alpha ->
    (gated) encoder/decoder reconstruction per step. ONE factory shared by the
    loop and the AOT contract registry. ``jit_kwargs`` carries the multi-device
    ``out_shardings`` pin (see the donation note below)."""
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    encoder_tau = float(cfg.algo.encoder.tau)
    num_critics = int(cfg.algo.critic.n)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    actor_freq = int(cfg.algo.actor.per_rank_update_freq)
    decoder_freq = int(cfg.algo.decoder.per_rank_update_freq)
    l2_lambda = float(cfg.algo.decoder.l2_lambda)
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    actor_tx, critic_tx, alpha_tx = txs["actor"], txs["critic"], txs["alpha"]
    encoder_tx, decoder_tx = txs["encoder"], txs["decoder"]
    # compile the Learn/* stats only when the telemetry learning plane is on
    learn_on = learn_stats.enabled(cfg)

    def _flat_img(x):
        # fold frame-stack dims into channels: [..., S, C, H, W] -> [..., S*C, H, W]
        return x.reshape(*x.shape[:-4], -1, *x.shape[-2:]) if x.ndim >= 5 else x

    def _norm(batch, prefix=""):
        out = {}
        for k in cnn_keys:
            out[k] = _flat_img(batch[prefix + k]) / 255.0
        for k in mlp_keys:
            out[k] = batch[prefix + k]
        return out

    def critic_loss_fn(cg, params, batch, step_key):
        p = {**params, **cg}
        next_obs = _norm(batch, "next_")
        obs = _norm(batch)
        feat_next_actor = agent.features(params, next_obs, side="actor")
        mean, std = agent.actor.apply({"params": params["actor"]}, feat_next_actor)
        next_actions, next_logprobs = squash_and_logprob(
            mean, std, step_key, agent.action_scale, agent.action_bias
        )
        target_feat = agent.features(params, next_obs, target=True)
        target_q = agent.qfs.apply({"params": params["target"]["qfs"]}, target_feat, next_actions)
        alpha = jnp.exp(params["log_alpha"])
        min_target = jnp.min(target_q, axis=-1, keepdims=True) - alpha * next_logprobs
        next_qf_value = batch["rewards"] + (1 - batch["terminated"]) * gamma * min_target
        feat = agent.features(p, obs)
        qf_values = agent.qfs.apply({"params": cg["qfs"]}, feat, batch["actions"])
        loss = critic_loss(qf_values, jax.lax.stop_gradient(next_qf_value), num_critics)
        # aux for the learn-stats block: Q statistics + per-sample TD error
        return loss, (qf_values, qf_values - next_qf_value)

    def actor_loss_fn(ag, params, batch, step_key):
        p = {**params, **ag}
        obs = _norm(batch)
        feat = agent.features(p, obs, side="actor", detach_encoder_features=True)
        mean, std = agent.actor.apply({"params": ag["actor"]}, feat)
        actions, logprobs = squash_and_logprob(mean, std, step_key, agent.action_scale, agent.action_bias)
        feat_c = agent.features(params, obs, detach_encoder_features=True)
        qf_values = agent.qfs.apply({"params": params["qfs"]}, feat_c, actions)
        min_qf = jnp.min(qf_values, axis=-1, keepdims=True)
        alpha = jnp.exp(jax.lax.stop_gradient(params["log_alpha"]))
        return policy_loss(alpha, logprobs, min_qf), logprobs

    def alpha_loss_fn(log_alpha, logprobs):
        return entropy_loss(log_alpha, jax.lax.stop_gradient(logprobs), target_entropy)

    def reconstruction_loss_fn(eg_dg, params, batch, step_key):
        p = {**params, **{k: v for k, v in eg_dg.items() if k != "decoder"}}
        obs = _norm(batch)
        hidden = agent.features(p, obs)
        recon = agent.reconstruct({**params, "decoder": eg_dg["decoder"]}, hidden)
        l2 = 0.5 * jnp.sum(jnp.square(hidden), axis=-1).mean()
        loss = l2_lambda * l2
        for k in cnn_dec_keys:
            target = preprocess_obs(_flat_img(batch[k]), step_key, bits=5)
            loss = loss + jnp.mean(jnp.square(target - recon[k]))
        for k in mlp_dec_keys:
            loss = loss + jnp.mean(jnp.square(batch[k] - recon[k]))
        return loss

    # donate_argnums: XLA reuses the params/opt-state buffers in place instead of
    # copying the whole train state every round (callers always rebind to the
    # returned trees, so the invalidated inputs are never read again).
    # out_shardings (via jit_kwargs) pins the state outputs on multi-device
    # meshes — see the sac.py note (PR 8 residual; build_state_shardings).
    @partial(jax.jit, donate_argnums=(0, 1), **(jit_kwargs or {}))
    def train_phase(params, opt_state, data, cum_steps, train_key):
        G = data["rewards"].shape[0]
        keys = jax.random.split(jnp.asarray(train_key), G)

        def step(carry, inp):
            params, opt_state, cum = carry
            batch, k = inp
            k_critic, k_actor, k_rec = jax.random.split(k, 3)

            # critic
            cg = critic_group(params)
            (qf_loss, (qf_values, td_error)), qf_grads = jax.value_and_grad(
                critic_loss_fn, has_aux=True
            )(cg, params, batch, k_critic)
            new_cg, new_copt = _masked_update(critic_tx, qf_grads, opt_state["critic"], cg, 1)
            params = {**params, **new_cg}
            opt_state = {**opt_state, "critic": new_copt}

            # target EMA (critic tau + encoder tau), gated on cumulative steps
            do_ema = (cum % target_freq) == 0
            new_target = {}
            for part, part_tau in (("qfs", tau), ("conv", encoder_tau), ("mlp_enc", encoder_tau), ("critic_cnn_fc", encoder_tau)):
                if part in params["target"]:
                    new_target[part] = jax.tree_util.tree_map(
                        lambda t, c: jnp.where(do_ema, part_tau * c + (1 - part_tau) * t, t),
                        params["target"][part],
                        params[part],
                    )
            params = {**params, "target": new_target}

            # actor + alpha, gated
            do_actor = ((cum % actor_freq) == 0).astype(jnp.float32)
            ag = actor_group(params)
            (a_loss, logprobs), a_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
                ag, params, batch, k_actor
            )
            new_ag, new_aopt = _masked_update(actor_tx, a_grads, opt_state["actor"], ag, do_actor)
            params = {**params, **new_ag}
            opt_state = {**opt_state, "actor": new_aopt}

            al_loss, al_grads = jax.value_and_grad(alpha_loss_fn)(params["log_alpha"], logprobs)
            new_la, new_alopt = _masked_update(
                alpha_tx, al_grads, opt_state["alpha"], params["log_alpha"], do_actor
            )
            params = {**params, "log_alpha": new_la}
            opt_state = {**opt_state, "alpha": new_alopt}

            # encoder/decoder reconstruction, gated
            do_dec = ((cum % decoder_freq) == 0).astype(jnp.float32)
            eg = encoder_group(params)
            eg_dg = {**eg, "decoder": params["decoder"]}
            rec_loss, rec_grads = jax.value_and_grad(reconstruction_loss_fn)(
                eg_dg, params, batch, k_rec
            )
            enc_grads = {k: v for k, v in rec_grads.items() if k != "decoder"}
            new_eg, new_eopt = _masked_update(encoder_tx, enc_grads, opt_state["encoder"], eg, do_dec)
            new_dg, new_dopt = _masked_update(
                decoder_tx, rec_grads["decoder"], opt_state["decoder"], params["decoder"], do_dec
            )
            params = {**params, **new_eg, "decoder": new_dg}
            opt_state = {**opt_state, "encoder": new_eopt, "decoder": new_dopt}

            # device-side training-health block (utils/learn_stats.py). Update
            # ratios are omitted here: _masked_update folds the gate into the
            # returned params, so the raw update magnitude is not materialized.
            learn = learn_stats.maybe(learn_on, lambda: {
                **learn_stats.group_stats(
                    "critic", grads=qf_grads, params=new_cg, opt_state=new_copt
                ),
                **learn_stats.group_stats(
                    "actor", grads=a_grads, params=new_ag, opt_state=new_aopt
                ),
                **learn_stats.group_stats("alpha", grads=al_grads),
                **learn_stats.group_stats("encoder", grads=enc_grads, params=new_eg),
                **learn_stats.group_stats("decoder", grads=rec_grads["decoder"], params=new_dg),
                **learn_stats.value_stats(qf_values, prefix="q"),
                **learn_stats.td_quantiles(td_error),
                **learn_stats.entropy_stats(-logprobs),
                "Learn/alpha": jnp.exp(params["log_alpha"]).reshape(()),
                "Learn/loss/critic": qf_loss,
                "Learn/loss/actor": a_loss,
                "Learn/loss/alpha": al_loss,
                "Learn/loss/reconstruction": rec_loss,
            })
            return (params, opt_state, cum + 1), (
                jnp.stack([qf_loss, a_loss, al_loss, rec_loss]),
                learn,
            )

        (params, opt_state, _), (losses, learn) = jax.lax.scan(
            step, (params, opt_state, cum_steps), (data, keys)
        )
        return params, opt_state, losses.mean(axis=0), learn_stats.reduce_stacked(learn)

    return train_phase


@register_fused_program(
    "sac_ae.train_phase",
    min_donated=2,
    doc="fused SAC-AE update (critic/actor/alpha + gated encoder-decoder reconstruction)",
)
def _aot_train_program():
    """Tiny pixel SAC-AE agent through the loop's own factory."""
    from sheeprl_tpu.analysis.programs import tiny_fabric
    from sheeprl_tpu.config import compose

    cfg = compose(
        [
            "exp=sac_ae",
            "env=dummy",
            "fabric.accelerator=cpu",
            "env.num_envs=2",
            "env.capture_video=False",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
            "algo.mlp_keys.decoder=[state]",
            "algo.dense_units=16",
            "algo.mlp_layers=1",
            "algo.per_rank_batch_size=2",
            "buffer.memmap=False",
            "metric.log_level=0",
            # lower the GROWN program (Learn/* stats compile in under telemetry)
            "metric.telemetry.enabled=true",
        ]
    )
    fabric = tiny_fabric()
    obs_space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8),
            "state": gym.spaces.Box(-np.inf, np.inf, (8,), np.float32),
        }
    )
    action_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
    agent, params = build_agent(fabric, cfg, obs_space, action_space, jax.random.PRNGKey(0), None)
    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    train_phase = make_train_phase(agent, cfg, txs, target_entropy=-2.0)
    G, B = 1, int(cfg.algo.per_rank_batch_size)
    rng = np.random.default_rng(0)

    def _obs(prefix=""):
        return {
            prefix + "rgb": rng.integers(0, 255, (G, B, 3, 64, 64)).astype(np.uint8),
            prefix + "state": rng.normal(size=(G, B, 8)).astype(np.float32),
        }

    data = {
        **_obs(),
        **_obs("next_"),
        "actions": rng.normal(size=(G, B, 2)).astype(np.float32),
        "rewards": rng.normal(size=(G, B, 1)).astype(np.float32),
        "terminated": np.zeros((G, B, 1), np.float32),
    }
    args = (params, opt_state, data, jnp.asarray(0), np.asarray(jax.random.PRNGKey(1)))
    return train_phase, args


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    total_num_envs = int(cfg.env.num_envs * world_size)
    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * total_num_envs + i,
                rank * total_num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(total_num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if len(cnn_keys) + len(mlp_keys) == 0:
        raise RuntimeError("You should specify at least one CNN or MLP key for the encoder")
    obs_keys = cnn_keys + mlp_keys
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cnn_keys)
        fabric.print("Encoder MLP keys:", mlp_keys)

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(
        fabric, cfg, observation_space, action_space, agent_key, state["agent"] if state else None
    )
    act_dim = int(np.prod(action_space.shape))
    target_entropy = -float(act_dim)

    # five optimizers (reference sac_ae.py:211-248) — shared construction with
    # the AOT registry (build_optimizers/init_opt_state, module level)
    txs = build_optimizers(cfg)
    opt_state = init_opt_state(txs, params)
    if state is not None:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // total_num_envs if not cfg.dry_run else 1
    rb = ReplayBuffer(
        buffer_size,
        total_num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        obs_keys=tuple(obs_keys),
    )
    if state is not None and "rb" in state:
        rb = state["rb"]

    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * cfg.env.num_envs if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(total_num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state is not None:
        ratio.load_state_dict(state["ratio"])

    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    # ---------------- jitted programs ----------------

    @jax.jit
    def act_fn(params, obs: Dict[str, jax.Array], key):
        # PRNG chain advances inside the jitted program (un-jitted per-step
        # jax.random.split costs ~0.5 ms of host dispatch)
        key, step_key = jax.random.split(key)
        feat = agent.features(params, obs, side="actor")
        mean, std = agent.actor.apply({"params": params["actor"]}, feat)
        actions, _ = squash_and_logprob(mean, std, step_key, agent.action_scale, agent.action_bias)
        return actions, key

    # act/train placement split (shared ActPlacement design): the act view carries
    # exactly what act_fn reads — the shared conv trunk, the actor-side cnn fc,
    # the mlp encoder and the actor head (agent.features(side="actor") + actor).
    act = ActPlacement(
        fabric,
        lambda p: {k: p[k] for k in ("conv", "actor_cnn_fc", "mlp_enc", "actor") if k in p},
    )

    # the fused train program — ONE factory (make_train_phase) shared with the
    # AOT contract registry, so the program `sheeprl.py lint --aot` lowers is
    # the program this loop runs. out_shardings pins the state outputs on
    # multi-device meshes — see make_train_phase's donation note.
    from sheeprl_tpu.parallel.sharding import build_state_shardings

    # extra_outputs=2: the losses vector AND the Learn/* stats block
    _state_shardings = build_state_shardings(fabric, params, opt_state, extra_outputs=2)
    _train_jit_kwargs = (
        {"out_shardings": tuple(_state_shardings)} if _state_shardings is not None else {}
    )
    train_phase = make_train_phase(agent, cfg, txs, target_entropy, jit_kwargs=_train_jit_kwargs)

    if world_size > 1:
        params = fabric.replicate_pytree(params)
        opt_state = fabric.replicate_pytree(opt_state)

    act_params = act.view(params)
    key = act.place(key)

    # replay hot path: async prefetcher (sampling + sharded staging off-thread) or
    # the exact inline path when buffer.prefetch.enabled=false
    sampler = make_replay_sampler(
        rb,
        cfg.buffer.get("prefetch"),
        sample_kwargs=dict(batch_size=cfg.algo.per_rank_batch_size * world_size),
        uint8_keys=cnn_keys,
        sharding=fabric.sharding(None, "data") if world_size > 1 else None,
        name="sac-ae-replay-prefetch",
    )
    telemetry.attach_sampler(sampler)

    # ---------------- main loop ----------------
    cumulative_per_rank_gradient_steps = 0
    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]

    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and state is None:
                actions = envs.action_space.sample()
            else:
                jobs = prepare_obs(
                    fabric, obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=total_num_envs
                )
                actions, key = act_fn(act_params, jobs, key)
                actions = np.asarray(actions)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                np.asarray(actions).reshape(envs.action_space.shape)
            )
            rewards = np.asarray(rewards, dtype=np.float32).reshape(total_num_envs, -1)

        ep_info = infos.get("final_info", infos)
        if "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(total_num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
        final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
        if final_obs_arr is not None:
            for idx in range(total_num_envs):
                if final_obs_arr[idx] is not None:
                    for k in obs_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])

        for k in obs_keys:
            step_data[k] = np.asarray(obs[k]).reshape(1, total_num_envs, *np.asarray(obs[k]).shape[1:])
            step_data[f"next_{k}"] = real_next_obs[k][np.newaxis]
        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, total_num_envs, -1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, total_num_envs, -1)
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, total_num_envs, -1)
        step_data["rewards"] = rewards[np.newaxis]
        sampler.add(step_data, validate_args=cfg.buffer.validate_args)

        obs = next_obs

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio((policy_step - prefill_steps * policy_steps_per_iter) / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(per_rank_gradient_steps)
                    key, train_key = jax.random.split(key)
                    # one-shot injected learning pathology (resilience.fault=
                    # lr_spike): identity unless armed this iteration
                    params = apply_armed_learn_fault(params)
                    params, opt_state, mean_losses, learn = train_phase(
                        params,
                        opt_state,
                        data,
                        jnp.asarray(cumulative_per_rank_gradient_steps),
                        np.asarray(train_key),
                    )
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    act_params = act.view(params)
                    telemetry.observe_train(per_rank_gradient_steps, mean_losses)
                    telemetry.observe_learn(learn)
                    if telemetry.wants_program("train_phase"):
                        telemetry.register_program(
                            "train_phase",
                            train_phase,
                            (
                                params,
                                opt_state,
                                data,
                                jnp.asarray(cumulative_per_rank_gradient_steps),
                                np.asarray(train_key),
                            ),
                            units=per_rank_gradient_steps,
                        )
                    if aggregator and not aggregator.disabled:
                        losses_np = np.asarray(mean_losses)
                        aggregator.update("Loss/value_loss", losses_np[0])
                        aggregator.update("Loss/policy_loss", losses_np[1])
                        aggregator.update("Loss/alpha_loss", losses_np[2])
                        aggregator.update("Loss/reconstruction_loss", losses_np[3])

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (policy_step - last_log) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (policy_step - last_log)
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": params,
                "opt_state": opt_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            # quiesce the prefetch worker so the pickled buffer (incl. its RNG
            # state) is not a torn mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    sampler.close()
    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(agent, params, fabric, cfg, log_dir)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
