"""P2E-DV3 support (reference: sheeprl/algos/p2e_dv3/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import (  # noqa: F401 — shared with DV3
    init_moments,
    prepare_obs,
    test,
    update_moments,
)

AGGREGATOR_KEYS = {
    # dreamer-native keys: the finetuning phase delegates to the dreamer train
    # program, which emits the unsuffixed names
    "Loss/policy_loss",
    "Loss/value_loss",
    "Grads/actor",
    "Grads/critic",
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Loss/policy_loss_exploration",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/ensemble_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor_task",
    "Grads/critic_task",
    "Grads/actor_exploration",
    "Grads/ensemble",
    # per-exploration-critic metrics are dynamically suffixed with the critic key
    "Loss/value_loss_exploration",
    "Grads/critic_exploration",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
}
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "critics_exploration",
    "actor_task",
    "critic_task",
    "target_critic_task",
    "moments_task",
    "moments_exploration",
}
