"""Plan2Explore (Dreamer-V3 backbone) agent (reference sheeprl/algos/p2e_dv3/agent.py:
build_agent:24-212): the full DV3 world model plus a disagreement ensemble, a second
(exploration) actor and one critic per exploration reward stream.

Params layout: {"world_model", "actor_task", "critic_task", "target_critic_task",
"actor_exploration", "critics_exploration": {k: {"module", "target"}}, "ensembles"}.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import DV3Agent, MLPHead
from sheeprl_tpu.algos.dreamer_v3.agent import build_agent as build_dv3_agent


class EnsembleHeads(nn.Module):
    """N independent next-state predictors with stacked params — one vmapped apply
    evaluates all ensemble members (the reference loops over N modules,
    p2e_dv3_exploration.py:208-220). Output [n, ..., out_dim]."""

    n: int
    units: int
    n_layers: int
    output_dim: int
    activation: Any = "silu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        ensemble = nn.vmap(
            MLPHead,
            in_axes=None,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            axis_size=self.n,
        )
        return ensemble(
            units=self.units,
            n_layers=self.n_layers,
            output_dim=self.output_dim,
            activation=self.activation,
            dtype=self.dtype,
        )(x)


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV3Agent, EnsembleHeads, Dict[str, Any]]:
    """DV3 agent + exploration heads + ensembles. The returned DV3Agent's ``actor``/
    ``critic`` modules serve both the task and exploration parameter sets (identical
    architectures, independent params)."""
    k_dv3, k_expl, k_ens, k_crit = jax.random.split(key, 4)
    agent, dv3_params = build_dv3_agent(fabric, actions_dim, is_continuous, cfg, obs_space, k_dv3)

    latent = jnp.zeros((1, agent.latent_state_size), jnp.float32)
    actor_exploration_params = agent.actor.init(k_expl, latent)["params"]
    critics_exploration: Dict[str, Dict[str, Any]] = {}
    for i, (name, c) in enumerate(dict(cfg.algo.critics_exploration).items()):
        cp = agent.critic.init(jax.random.fold_in(k_crit, i), latent)["params"]
        critics_exploration[name] = {
            "module": cp,
            "target": jax.tree_util.tree_map(jnp.copy, cp),
        }

    ens_cfg = cfg.algo.ensembles
    ensembles = EnsembleHeads(
        n=int(ens_cfg.n),
        units=ens_cfg.dense_units,
        n_layers=ens_cfg.mlp_layers,
        output_dim=agent.stoch_state_size,
        activation=ens_cfg.dense_act,
        dtype=fabric.compute_dtype,
    )
    act_dim = int(np.sum(actions_dim))
    ens_in = jnp.zeros((1, agent.latent_state_size + act_dim), jnp.float32)
    ensembles_params = ensembles.init(k_ens, ens_in)["params"]

    params = {
        "world_model": dv3_params["world_model"],
        "actor_task": dv3_params["actor"],
        "critic_task": dv3_params["critic"],
        "target_critic_task": dv3_params["target_critic"],
        "actor_exploration": actor_exploration_params,
        "critics_exploration": critics_exploration,
        "ensembles": ensembles_params,
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    if getattr(fabric, "model_parallel", False):
        # the DV3 subtree is already sharded by build_dv3_agent's jitted init;
        # device_put with the same rule is a no-op there and lands the eager
        # exploration heads/ensembles (and any resumed tree) in their shards
        params = fabric.shard_params(params)
    return agent, ensembles, params


def player_params(params: Dict[str, Any], actor_type: str) -> Dict[str, Any]:
    """View of the p2e params pytree in the layout PlayerDV3 expects."""
    return {
        "world_model": params["world_model"],
        "actor": params["actor_exploration"] if actor_type == "exploration" else params["actor_task"],
    }
