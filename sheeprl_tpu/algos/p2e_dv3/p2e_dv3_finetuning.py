"""Plan2Explore DV3 — finetuning phase (capability parity with
sheeprl/algos/p2e_dv3/p2e_dv3_finetuning.py:28-330): resume the exploration
checkpoint's world model / task heads, optionally inherit the exploration replay
buffer, act with the exploration actor until ``learning_starts`` then switch to the
task actor, and train with the standard Dreamer-V3 program."""

from __future__ import annotations

import os
import pathlib
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_phase
from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
from sheeprl_tpu.algos.p2e_dv3.agent import build_agent, player_params
from sheeprl_tpu.algos.p2e_dv3.utils import prepare_obs, test
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.mfu import unit_avals
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


@register_algorithm()
def main(fabric, cfg: Dict[str, Any], exploration_cfg: Dict[str, Any]):
    rank = fabric.global_rank
    world_size = fabric.world_size

    ckpt_path = pathlib.Path(cfg.checkpoint.exploration_ckpt_path)
    resume = cfg.checkpoint.resume_from is not None
    state = fabric.load(pathlib.Path(cfg.checkpoint.resume_from) if resume else ckpt_path)

    # the models must match the exploration phase (reference
    # p2e_dv3_finetuning.py:46-70)
    for k in (
        "gamma", "lmbda", "horizon", "dense_units", "mlp_layers", "dense_act", "cnn_act",
        "unimix", "hafner_initialization", "world_model", "actor", "critic",
        "cnn_keys", "mlp_keys",
    ):
        if k in exploration_cfg.algo:
            cfg.algo[k] = exploration_cfg.algo[k]
    cfg.env.clip_rewards = exploration_cfg.env.clip_rewards
    if cfg.buffer.get("load_from_exploration", False) and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs
    cfg.env.frame_stack = -1

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = build_telemetry(fabric, cfg, log_dir, logger=logger)
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    num_envs = int(cfg.env.num_envs)
    envs = vectorized_env(
        [
            make_env(
                cfg,
                cfg.seed + rank * num_envs + i,
                rank * num_envs,
                log_dir if rank == 0 else None,
                "train",
                vector_env_idx=i,
            )
            for i in range(num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, _, p2e_params = build_agent(
        fabric, actions_dim, is_continuous, cfg, observation_space, agent_key, state["agent"]
    )
    # DV3-layout view of the p2e pytree: the task heads are trained
    params = {
        "world_model": p2e_params["world_model"],
        "actor": p2e_params["actor_task"],
        "critic": p2e_params["critic_task"],
        "target_critic": p2e_params["target_critic_task"],
    }
    actor_exploration_params = p2e_params["actor_exploration"]
    player = PlayerDV3(agent, num_envs, cnn_keys, mlp_keys)
    actor_type = cfg.algo.player.actor_type

    def _tx(opt_cfg, clip):
        base = instantiate(opt_cfg)
        if clip is not None and clip > 0:
            return optax.chain(optax.clip_by_global_norm(clip), base)
        return base

    world_tx = _tx(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = _tx(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = _tx(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    opt_state = {
        "world_model": world_tx.init(params["world_model"]),
        "actor": actor_tx.init(params["actor"]),
        "critic": critic_tx.init(params["critic"]),
    }
    if resume and "opt_state" in state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
    moments_state = init_moments()
    if resume and "moments" in state:
        moments_state = jax.tree_util.tree_map(jnp.asarray, state["moments"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    buffer_size = cfg.buffer.size // int(num_envs * world_size) if not cfg.dry_run else 8
    rb = EnvIndependentReplayBuffer(
        buffer_size,
        n_envs=num_envs,
        obs_keys=tuple(obs_keys),
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        buffer_cls=SequentialReplayBuffer,
    )
    if "rb" in state and (
        cfg.buffer.get("load_from_exploration", False) or (resume and cfg.buffer.checkpoint)
    ):
        rb = state["rb"]

    from sheeprl_tpu.parallel.sharding import build_state_shardings

    train_phase = make_train_phase(
        agent, cfg, world_tx, actor_tx, critic_tx,
        state_shardings=build_state_shardings(fabric, params, opt_state, moments_state),
    )

    start_iter = (state["iter_num"] // world_size) + 1 if resume else 1
    policy_step = state["iter_num"] * num_envs if resume else 0
    last_log = state["last_log"] if resume else 0
    last_checkpoint = state["last_checkpoint"] if resume else 0
    policy_steps_per_iter = int(num_envs * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if resume:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if resume:
        ratio.load_state_dict(state["ratio"])

    # replay hot path: async prefetcher (sampling + sharded staging off-thread) or the
    # exact inline path when buffer.prefetch.enabled=false. Built AFTER the resume
    # block above so a restored batch size shapes the staged units.
    sampler = make_replay_sampler(
        rb,
        cfg.buffer.get("prefetch"),
        sample_kwargs=dict(
            batch_size=cfg.algo.per_rank_batch_size * world_size,
            sequence_length=cfg.algo.per_rank_sequence_length,
        ),
        uint8_keys=cnn_keys,
        sharding=fabric.sharding(None, None, "data") if fabric.num_devices > 1 else None,
        name="p2e-dv3-ft-replay-prefetch",
    )
    telemetry.attach_sampler(sampler)

    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    def _act_params():
        p2e_view = {
            "world_model": params["world_model"],
            "actor_task": params["actor"],
            "actor_exploration": actor_exploration_params,
        }
        return player_params(p2e_view, actor_type)

    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states(_act_params())

    cumulative_per_rank_gradient_steps = 0
    train_step = 0
    last_train = 0
    act_dim = int(np.sum(actions_dim))

    for iter_num in range(start_iter, total_iters + 1):
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            jobs = prepare_obs(fabric, obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
            actions, key = player.get_actions(_act_params(), jobs, key)
            actions = np.asarray(actions)
            if is_continuous:
                real_actions = actions
            else:
                splits = np.cumsum(actions_dim)[:-1]
                real_actions = np.stack(
                    [b.argmax(-1) for b in np.split(actions, splits, axis=-1)], axis=-1
                )

            step_data["actions"] = actions.reshape((1, num_envs, -1)).astype(np.float32)
            sampler.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])

        ep_info = infos.get("final_info", infos)
        if (cfg.metric.log_level > 0 or telemetry.enabled) and "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
        final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
        if final_obs_arr is not None:
            for idx in range(num_envs):
                if final_obs_arr[idx] is not None:
                    for k in obs_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])

        for k in obs_keys:
            step_data[k] = np.asarray(next_obs[k])[np.newaxis]
        obs = next_obs

        rewards = np.asarray(rewards, dtype=np.float32).reshape((1, num_envs, -1))
        step_data["terminated"] = np.asarray(terminated, np.float32).reshape((1, num_envs, -1))
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape((1, num_envs, -1))
        step_data["rewards"] = clip_rewards_fn(rewards)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (real_next_obs[k][dones_idxes])[np.newaxis]
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, reset_envs, act_dim), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            sampler.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            step_data["rewards"][:, dones_idxes] = 0.0
            step_data["terminated"][:, dones_idxes] = 0.0
            step_data["truncated"][:, dones_idxes] = 0.0
            step_data["is_first"][:, dones_idxes] = 1.0
            player.init_states(_act_params(), dones_idxes)

        if iter_num >= learning_starts:
            # after the prefill the player switches to the task actor (reference
            # p2e_dv3_finetuning.py:350-352)
            if actor_type != "task":
                actor_type = "task"
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(per_rank_gradient_steps)
                    key, train_key = jax.random.split(key)
                    # one-shot injected learning pathology (resilience.fault=
                    # lr_spike): identity unless armed this iteration
                    params = apply_armed_learn_fault(params)
                    params, opt_state, moments_state, metrics = train_phase(
                        params,
                        opt_state,
                        moments_state,
                        data,
                        jnp.asarray(cumulative_per_rank_gradient_steps),
                        np.asarray(train_key),
                    )
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                    telemetry.observe_train(per_rank_gradient_steps, metrics)
                    telemetry.observe_learn(metrics)
                    if telemetry.wants_program("train_step"):
                        batch_avals = unit_avals(data)
                        telemetry.register_program(
                            "train_step",
                            train_phase.train_step,
                            (
                                params,
                                opt_state,
                                moments_state,
                                batch_avals,
                                jnp.asarray(cumulative_per_rank_gradient_steps),
                                jnp.asarray(train_key),
                            ),
                            units=1,
                        )
                    if aggregator and not aggregator.disabled:
                        for mk, mv in metrics.items():
                            aggregator.update(mk, float(np.asarray(mv)))

        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step
            last_train = train_step

        # a preemption forces an out-of-cadence emergency checkpoint through the
        # same callback path, then exits the loop
        preempted = resilience.preempt_requested()
        if (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
            or preempted
        ):
            last_checkpoint = policy_step
            full_agent = {
                **p2e_params,
                "world_model": params["world_model"],
                "actor_task": params["actor"],
                "critic_task": params["critic"],
                "target_critic_task": params["target_critic"],
            }
            ckpt_state = {
                "agent": full_agent,
                "opt_state": opt_state,
                "moments": moments_state,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            # quiesce the prefetch worker so the pickled buffer (incl. its RNG
            # state) is not a torn mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            break

    sampler.close()
    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test(player, _act_params(), fabric, cfg, log_dir, greedy=False)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()
