"""Offline Dreamer contract constants + shared helpers (reference
sheeprl/algos/offline_dreamer/utils.py:20-37; test/prepare_obs are the Dreamer-V3
ones — the player exposes the same interface)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401 — shared

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/concept_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}
