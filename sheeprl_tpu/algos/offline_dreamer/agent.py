"""Offline Dreamer agent: Dreamer-V3 plus a Concept-Bottleneck World Model.

Capability parity with reference sheeprl/algos/offline_dreamer/agent.py: the ``CEM``
concept-embedding module (reference agent.py:943-1026) maps the RSSM latent into
``sum(concept_bins)`` concept probabilities + per-concept embeddings + one residual
(non-concept) embedding; every head (decoder/reward/continue/actor/critic) then
consumes this concept latent instead of the raw one (reference agent.py:1101-1299,
CBWM at agent.py:1030). With ``use_cbm: False`` the agent degenerates to Dreamer-V3.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    CNNDecoder,
    CNNEncoder,
    Decoder,
    DV3Agent,
    Encoder,
    MLPDecoder,
    MLPEncoder,
    MLPHead,
    RecurrentModel,
    actor_sample,
)


class CEM(nn.Module):
    """Concept Embedding Module (reference CEM, offline_dreamer/agent.py:943-1026).

    For each concept ``c`` a context head produces ``concept_bins[c]`` candidate
    embeddings of size ``emb_size``; a prob head scores the bins; the concept
    embedding is the prob-weighted sum of the candidates. One extra context head
    produces the residual (non-concept) embedding. Output latent =
    ``concat(all bin probs, all concept embeddings, residual)`` of size
    ``sum(concept_bins) + (n_concepts + 1) * emb_size``.
    """

    n_concepts: int
    concept_bins: Tuple[int, ...]
    emb_size: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        probs_blocks = []
        logits_blocks = []
        emb_blocks = []
        for c in range(self.n_concepts):
            bins = self.concept_bins[c]
            context = nn.Dense(bins * self.emb_size, dtype=self.dtype, name=f"context_{c}")(latent)
            logits = nn.Dense(bins, dtype=self.dtype, name=f"prob_{c}")(context)
            probs = jax.nn.softmax(logits, axis=-1)
            # prob-weighted mixture of the per-bin candidate embeddings
            candidates = context.reshape(*context.shape[:-1], bins, self.emb_size)
            emb = jnp.sum(candidates * probs[..., None], axis=-2)
            probs_blocks.append(probs)
            logits_blocks.append(logits)
            emb_blocks.append(emb)
        residual = nn.Dense(self.emb_size, dtype=self.dtype, name=f"context_{self.n_concepts}")(latent)
        all_probs = jnp.concatenate(probs_blocks, axis=-1)
        all_logits = jnp.concatenate(logits_blocks, axis=-1)
        concept_emb = jnp.concatenate(emb_blocks, axis=-1)
        cem_latent = jnp.concatenate([all_probs, concept_emb, residual], axis=-1)
        return cem_latent, all_logits, concept_emb, residual


def cem_latent_size(cfg) -> int:
    cbm = cfg.algo.world_model.cbm_model
    return int(sum(cbm.concept_bins) + (cbm.n_concepts + 1) * cbm.emb_size)


@dataclass
class ODV3Agent(DV3Agent):
    """DV3Agent + optional CEM bottleneck. When ``use_cbm`` the heads read the CEM
    latent and ``wm_params["cem"]`` holds the bottleneck parameters."""

    cem: Optional[CEM] = None
    use_cbm: bool = False

    @property
    def head_latent_size(self) -> int:
        if self.use_cbm:
            return int(
                sum(self.cem.concept_bins) + (self.cem.n_concepts + 1) * self.cem.emb_size
            )
        return self.latent_state_size

    def apply_cem(self, wm_params: Dict, latent: jax.Array):
        """Returns (head_latent, concept_logits, concept_emb, residual); identity
        (with empty aux) when the bottleneck is disabled."""
        if not self.use_cbm:
            return latent, None, None, None
        return self.cem.apply({"params": wm_params["cem"]}, latent)

    def imagination_scan(
        self,
        wm_params: Dict,
        actor_params: Dict,
        z0: jax.Array,
        h0: jax.Array,
        key: jax.Array,
        horizon: int,
    ) -> Tuple[jax.Array, jax.Array]:
        """Latent imagination with the CEM applied at every step (reference
        behaviour_learning, offline_dreamer.py:110-172): the recorded trajectory and
        the actor inputs are CEM latents; the RSSM dynamics still evolve (z, h)."""
        if not self.use_cbm:
            return super().imagination_scan(wm_params, actor_params, z0, h0, key, horizon)

        k0, kscan = jax.random.split(key)
        latent0, _, _, _ = self.apply_cem(wm_params, jnp.concatenate([z0, h0], axis=-1))
        pre = self.actor.apply({"params": actor_params}, jax.lax.stop_gradient(latent0))
        a0 = actor_sample(self, pre, k0)

        def step(carry, k):
            z, h, a = carry
            h = self._recurrent(wm_params, z, a, h)
            _, z = self._transition(wm_params, h, k)
            latent, _, _, _ = self.apply_cem(wm_params, jnp.concatenate([z, h], axis=-1))
            k_act = jax.random.fold_in(k, 1)
            pre = self.actor.apply({"params": actor_params}, jax.lax.stop_gradient(latent))
            a = actor_sample(self, pre, k_act)
            return (z, h, a), (latent, a)

        keys = jax.random.split(kscan, horizon)
        _, (latents, actions) = jax.lax.scan(step, (z0, h0, a0), keys)
        latents = jnp.concatenate([latent0[None], latents], axis=0)
        actions = jnp.concatenate([a0[None], actions], axis=0)
        return latents, actions


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[ODV3Agent, Dict[str, Any]]:
    """Role of reference offline_dreamer build_agent (agent.py:1055-1360): identical
    to the Dreamer-V3 build except every head's input is the CEM latent size."""
    wm_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    cbm_cfg = wm_cfg.cbm_model
    use_cbm = bool(cbm_cfg.use_cbm)
    dtype = fabric.compute_dtype
    if wm_cfg.get("decoupled_rssm", False):
        raise NotImplementedError(
            "decoupled_rssm is not implemented yet; set algo.world_model.decoupled_rssm=False"
        )

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    cnn_stages = int(np.log2(cfg.env.screen_size) - np.log2(4))
    eps = 1e-3

    cnn_encoder = (
        CNNEncoder(
            keys=cnn_keys,
            channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
            stages=cnn_stages,
            activation=cfg.algo.cnn_act,
            eps=eps,
            dtype=dtype,
        )
        if len(cnn_keys) > 0
        else None
    )
    mlp_encoder = (
        MLPEncoder(
            keys=mlp_keys,
            mlp_layers=wm_cfg.encoder.mlp_layers,
            dense_units=wm_cfg.encoder.dense_units,
            activation=cfg.algo.dense_act,
            eps=eps,
            dtype=dtype,
        )
        if len(mlp_keys) > 0
        else None
    )
    encoder = Encoder(cnn_encoder, mlp_encoder)

    stochastic_size = wm_cfg.stochastic_size
    discrete_size = wm_cfg.discrete_size
    stoch_state_size = stochastic_size * discrete_size
    recurrent_state_size = wm_cfg.recurrent_model.recurrent_state_size
    latent_state_size = stoch_state_size + recurrent_state_size
    cem = (
        CEM(
            n_concepts=int(cbm_cfg.n_concepts),
            concept_bins=tuple(int(b) for b in cbm_cfg.concept_bins),
            emb_size=int(cbm_cfg.emb_size),
            dtype=dtype,
        )
        if use_cbm
        else None
    )
    head_latent_size = (
        int(sum(cbm_cfg.concept_bins) + (cbm_cfg.n_concepts + 1) * cbm_cfg.emb_size)
        if use_cbm
        else latent_state_size
    )

    recurrent_model = RecurrentModel(
        recurrent_state_size=recurrent_state_size,
        dense_units=wm_cfg.recurrent_model.dense_units,
        activation=cfg.algo.dense_act,
        eps=eps,
        dtype=dtype,
    )
    representation_model = MLPHead(
        units=wm_cfg.representation_model.hidden_size,
        n_layers=1,
        output_dim=stoch_state_size,
        activation=wm_cfg.representation_model.dense_act,
        eps=eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    transition_model = MLPHead(
        units=wm_cfg.transition_model.hidden_size,
        n_layers=1,
        output_dim=stoch_state_size,
        activation=wm_cfg.transition_model.dense_act,
        eps=eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    cnn_decoder = (
        CNNDecoder(
            keys=cnn_dec_keys,
            output_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_dec_keys],
            channels_multiplier=wm_cfg.observation_model.cnn_channels_multiplier,
            image_size=tuple(obs_space[cnn_dec_keys[0]].shape[-2:]),
            stages=cnn_stages,
            activation=cfg.algo.cnn_act,
            eps=eps,
            hafner_heads=cfg.algo.hafner_initialization,
            dtype=dtype,
        )
        if len(cnn_dec_keys) > 0
        else None
    )
    mlp_decoder = (
        MLPDecoder(
            keys=mlp_dec_keys,
            output_dims=[obs_space[k].shape[0] for k in mlp_dec_keys],
            mlp_layers=wm_cfg.observation_model.mlp_layers,
            dense_units=wm_cfg.observation_model.dense_units,
            activation=cfg.algo.dense_act,
            eps=eps,
            hafner_heads=cfg.algo.hafner_initialization,
            dtype=dtype,
        )
        if len(mlp_dec_keys) > 0
        else None
    )
    observation_model = Decoder(cnn_decoder, mlp_decoder)
    reward_model = MLPHead(
        units=wm_cfg.reward_model.dense_units,
        n_layers=wm_cfg.reward_model.mlp_layers,
        output_dim=wm_cfg.reward_model.bins,
        activation=cfg.algo.dense_act,
        eps=eps,
        head_init_scale=0.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    continue_model = MLPHead(
        units=wm_cfg.discount_model.dense_units,
        n_layers=wm_cfg.discount_model.mlp_layers,
        output_dim=1,
        activation=cfg.algo.dense_act,
        eps=eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    actor = Actor(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=actor_cfg.dense_units,
        mlp_layers=actor_cfg.mlp_layers,
        activation=actor_cfg.dense_act,
        eps=eps,
        dtype=dtype,
    )
    critic = MLPHead(
        units=critic_cfg.dense_units,
        n_layers=critic_cfg.mlp_layers,
        output_dim=critic_cfg.bins,
        activation=critic_cfg.dense_act,
        eps=eps,
        head_init_scale=0.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )

    agent = ODV3Agent(
        encoder=encoder,
        recurrent_model=recurrent_model,
        representation_model=representation_model,
        transition_model=transition_model,
        observation_model=observation_model,
        reward_model=reward_model,
        continue_model=continue_model,
        actor=actor,
        critic=critic,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        stochastic_size=stochastic_size,
        discrete_size=discrete_size,
        recurrent_state_size=recurrent_state_size,
        unimix=cfg.algo.unimix,
        actor_cfg={
            "init_std": actor_cfg.init_std,
            "min_std": actor_cfg.min_std,
            "max_std": actor_cfg.get("max_std", 1.0),
            "unimix": actor_cfg.get("unimix", cfg.algo.unimix),
            "action_clip": actor_cfg.get("action_clip", 1.0),
        },
        learnable_initial_recurrent_state=wm_cfg.learnable_initial_recurrent_state,
        cem=cem,
        use_cbm=use_cbm,
    )

    # -- init params -------------------------------------------------------------
    keys = jax.random.split(key, 11)
    dummy_obs = {}
    for k in cnn_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
    embed_dim_probe = encoder.init(keys[0], dummy_obs)
    embedded = encoder.apply(embed_dim_probe, dummy_obs)
    act_dim = int(np.sum(actions_dim))
    h = jnp.zeros((1, recurrent_state_size), jnp.float32)
    z = jnp.zeros((1, stoch_state_size), jnp.float32)
    latent = jnp.zeros((1, latent_state_size), jnp.float32)
    head_latent = jnp.zeros((1, head_latent_size), jnp.float32)

    wm_params = {
        "encoder": embed_dim_probe["params"],
        "recurrent_model": recurrent_model.init(
            keys[1], jnp.concatenate([z, jnp.zeros((1, act_dim), jnp.float32)], axis=-1), h
        )["params"],
        "representation_model": representation_model.init(
            keys[2], jnp.concatenate([h, embedded], axis=-1)
        )["params"],
        "transition_model": transition_model.init(keys[3], h)["params"],
        "observation_model": observation_model.init(keys[4], head_latent)["params"],
        "reward_model": reward_model.init(keys[5], head_latent)["params"],
        "continue_model": continue_model.init(keys[6], head_latent)["params"],
        "initial_recurrent_state": jnp.zeros((recurrent_state_size,), jnp.float32),
    }
    if use_cbm:
        wm_params["cem"] = cem.init(keys[9], latent)["params"]
    actor_params = actor.init(keys[7], head_latent)["params"]
    critic_params = critic.init(keys[8], head_latent)["params"]
    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        # a REAL copy: the donated train program must never see the same buffer in
        # two leaves (XLA rejects f(donate(a), donate(a)))
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    if getattr(fabric, "model_parallel", False):
        # data x model mesh: land every kernel in its rule-derived model-axis
        # shard (parallel/sharding.py); a 1-D mesh leaves this a no-op
        params = fabric.shard_params(params)
    return agent, params


class PlayerODV3:
    """Stateful env-interaction wrapper (reference PlayerODV3, agent.py:597-694):
    PlayerDV3 with the CEM applied to the latent before the actor (agent.py:693-694)."""

    def __init__(self, agent: ODV3Agent, num_envs: int, cnn_keys: Sequence[str], mlp_keys: Sequence[str]):
        self.agent = agent
        self.num_envs = num_envs
        self.cnn_keys = tuple(cnn_keys)
        self.mlp_keys = tuple(mlp_keys)
        self.actions: Optional[jax.Array] = None
        self.recurrent_state: Optional[jax.Array] = None
        self.stochastic_state: Optional[jax.Array] = None

        agent_ref = self.agent

        def _step(params, obs: Dict[str, jax.Array], a, h, z, key, greedy: bool):
            key, k_repr, k_act = jax.random.split(key, 3)
            wm = params["world_model"]
            embedded = agent_ref.encoder.apply({"params": wm["encoder"]}, obs)
            h = agent_ref._recurrent(wm, z, a, h)
            _, z = agent_ref._representation(wm, h, embedded, k_repr)
            latent = jnp.concatenate([z, h], axis=-1)
            latent, _, _, _ = agent_ref.apply_cem(wm, latent)
            pre = agent_ref.actor.apply({"params": params["actor"]}, latent)
            actions = actor_sample(agent_ref, pre, k_act, greedy=greedy)
            return actions, h, z, key

        self._step = jax.jit(_step, static_argnames=("greedy",))

    def init_states(self, params: Dict, reset_envs: Optional[Sequence[int]] = None) -> None:
        act_dim = int(np.sum(self.agent.actions_dim))
        if reset_envs is None or len(reset_envs) == 0:
            h0, z0 = self.agent.initial_state(params["world_model"], (self.num_envs,))
            self.actions = jnp.zeros((self.num_envs, act_dim), jnp.float32)
            self.recurrent_state = h0
            self.stochastic_state = z0
        else:
            idx = np.asarray(reset_envs)
            h0, z0 = self.agent.initial_state(params["world_model"], (len(idx),))
            self.actions = self.actions.at[idx].set(0.0)
            self.recurrent_state = self.recurrent_state.at[idx].set(h0)
            self.stochastic_state = self.stochastic_state.at[idx].set(z0)

    def get_actions(self, params: Dict, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False):
        """Returns ``(actions, key)`` — the advanced PRNG chain key."""
        actions, self.recurrent_state, self.stochastic_state, key = self._step(
            params, obs, self.actions, self.recurrent_state, self.stochastic_state, key, greedy
        )
        self.actions = actions
        return actions, key
