"""Offline Dreamer: Dreamer-V3 with a Concept-Bottleneck World Model (this fork's
in-repo specialty; reference sheeprl/algos/offline_dreamer/offline_dreamer.py:1-879).

The training loop *is* the Dreamer-V3 loop (the reference file is a fork of
dreamer_v3.py with the CEM inserted); here it reuses ``run_dreamer`` with three
injected pieces: the CBWM agent builder, the CEM-aware player, and a train-phase
whose world-model loss passes the latent through the CEM and adds the concept +
orthogonality terms (reference offline_dreamer.py:100-107, loss.py:122-136).
"""

from __future__ import annotations

from typing import Any, Dict

import jax

from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_phase, run_dreamer
from sheeprl_tpu.algos.offline_dreamer.agent import PlayerODV3, build_agent
from sheeprl_tpu.algos.offline_dreamer.loss import cbm_loss
from sheeprl_tpu.algos.offline_dreamer.utils import test  # noqa: F401 — re-export
from sheeprl_tpu.utils.registry import register_algorithm


def make_offline_train_phase(agent, cfg, world_tx, actor_tx, critic_tx, state_shardings=None):
    """Dreamer-V3 train phase with the CEM world-latent hook (when use_cbm)."""
    if not agent.use_cbm:
        return make_train_phase(
            agent, cfg, world_tx, actor_tx, critic_tx, state_shardings=state_shardings
        )

    def world_latent_hook(wm_params, latents, key):
        k_rand, k_concepts = jax.random.split(key)
        head_latents, concept_logits, concept_emb, residual = agent.apply_cem(wm_params, latents)
        # the reference also regularizes a random-latent pass (offline_dreamer.py:103-106)
        random_latent = jax.random.normal(k_rand, latents.shape, latents.dtype)
        _, _, rand_emb, rand_residual = agent.apply_cem(wm_params, random_latent)
        extra_loss, c_loss = cbm_loss(
            agent.cem, concept_logits, concept_emb, residual, rand_emb, rand_residual, k_concepts
        )
        return head_latents, extra_loss, {"Loss/concept_loss": c_loss}

    return make_train_phase(
        agent,
        cfg,
        world_tx,
        actor_tx,
        critic_tx,
        world_latent_hook=world_latent_hook,
        state_shardings=state_shardings,
    )


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    return run_dreamer(
        fabric,
        cfg,
        build_agent_fn=build_agent,
        player_cls=PlayerODV3,
        make_train_phase_fn=make_offline_train_phase,
        test_fn=test,
    )
