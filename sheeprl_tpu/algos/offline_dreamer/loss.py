"""Concept-bottleneck losses (reference sheeprl/algos/offline_dreamer/loss.py:10-144).

The reference's concept targets are an acknowledged placeholder — `#TODO replace with
actual concepts`, loss.py:125-127 draws random binary targets — so quality parity is
not defined; the capability surface (per-concept cross-entropy + orthogonal-projection
regularizer feeding the world-model loss) is what's reproduced here.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def concept_loss(
    concept_logits: jax.Array, target_probs: jax.Array, concept_bins: Sequence[int]
) -> jax.Array:
    """Sum over concepts of softmax cross-entropy between the predicted bin logits and
    the target bin distribution (reference get_concept_loss, loss.py:20-34)."""
    total = 0.0
    start = 0
    for bins in concept_bins:
        logits = concept_logits[..., start : start + bins]
        target = target_probs[..., start : start + bins]
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        total = total + (-jnp.sum(target * log_probs, axis=-1)).mean()
        start += bins
    return total


def orthogonal_projection_loss(embed1: jax.Array, embed2: jax.Array) -> jax.Array:
    """Mean |cosine similarity| between two embedding sets along the feature axis
    (reference OrthogonalProjectionLoss, loss.py:37-44)."""
    e1 = embed1 / (jnp.linalg.norm(embed1, axis=-1, keepdims=True) + 1e-6)
    e2 = embed2 / (jnp.linalg.norm(embed2, axis=-1, keepdims=True) + 1e-6)
    return jnp.abs(jnp.sum(e1 * e2, axis=-1)).mean()


def cbm_loss(
    cem,
    concept_logits: jax.Array,
    concept_emb: jax.Array,
    residual: jax.Array,
    rand_concept_emb: jax.Array,
    rand_residual: jax.Array,
    key: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Concept CE (against the reference's random placeholder targets, loss.py:127)
    plus orthogonality between each concept embedding and the residual, for both the
    real and the random latent pass (reference loss.py:130-135).

    Returns (cbm_loss, concept_loss) so the caller can log the CE term alone.
    """
    target = jax.random.bernoulli(key, 0.5, concept_logits.shape).astype(concept_logits.dtype)
    c_loss = concept_loss(concept_logits, target, cem.concept_bins)
    ortho = 0.0
    for c in range(cem.n_concepts):
        sl = slice(c * cem.emb_size, (c + 1) * cem.emb_size)
        ortho = ortho + orthogonal_projection_loss(concept_emb[..., sl], residual)
        ortho = ortho + orthogonal_projection_loss(rand_concept_emb[..., sl], rand_residual)
    return c_loss + ortho, c_loss
