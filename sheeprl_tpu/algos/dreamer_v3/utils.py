"""Dreamer-V3 support: metric whitelist, Moments return-normalizer, obs preparation
and the greedy test rollout (reference sheeprl/algos/dreamer_v3/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}


def init_moments() -> Dict[str, jax.Array]:
    return {"low": jnp.zeros(()), "high": jnp.zeros(())}


def update_moments(
    state: Dict[str, jax.Array],
    x: jax.Array,
    decay: float = 0.99,
    maximum: float = 1.0,
    percentile_low: float = 0.05,
    percentile_high: float = 0.95,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Percentile-EMA return normalizer (reference Moments, dreamer_v3/utils.py:40-64).
    Under SPMD the full (global) batch is visible inside the program, so the quantiles
    are already cross-replica — no explicit all_gather needed. Returns
    (offset, invscale, new_state)."""
    x = jax.lax.stop_gradient(x.astype(jnp.float32))
    low = jnp.quantile(x, percentile_low)
    high = jnp.quantile(x, percentile_high)
    new_low = decay * state["low"] + (1 - decay) * low
    new_high = decay * state["high"] + (1 - decay) * high
    invscale = jnp.maximum(1.0 / maximum, new_high - new_low)
    return new_low, invscale, {"low": new_low, "high": new_high}


# same [-0.5, 0.5] image normalization as DV2 (reference dreamer_v3/utils.py:81-93);
# shared so the host-array device-placement rationale lives in one place
from sheeprl_tpu.algos.dreamer_v2.utils import prepare_obs  # noqa: F401, E402


def test(
    player,
    params,
    fabric,
    cfg: Dict[str, Any],
    log_dir: str,
    test_name: str = "",
    greedy: bool = True,
):
    """Play one episode with the frozen params (reference utils.py:96-137)."""
    from sheeprl_tpu.utils.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test" + (f"_{test_name}" if test_name else ""))()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    player.num_envs = 1
    player.init_states(params)
    key = jax.random.PRNGKey(cfg.seed)
    actions_dim = player.agent.actions_dim
    while not done:
        jobs = prepare_obs(
            fabric, obs, cnn_keys=cfg.algo.cnn_keys.encoder, mlp_keys=cfg.algo.mlp_keys.encoder, num_envs=1
        )
        actions, key = player.get_actions(params, jobs, key, greedy=greedy)
        actions = np.asarray(actions)
        if player.agent.is_continuous:
            real_actions = actions[0]
        else:
            splits = np.cumsum(actions_dim)[:-1]
            real_actions = np.stack([b.argmax(-1) for b in np.split(actions[0], splits, axis=-1)], axis=-1)
        obs, reward, terminated, truncated, _ = env.step(real_actions.reshape(env.action_space.shape))
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(np.asarray(reward))
    fabric.print("Test - Reward:", cumulative_rew)
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()
