"""Dreamer-V3, decoupled actor–learner (MPMD) training.

The reference has NO decoupled Dreamer — this is the BASELINE.md north-star
topology ("DV3 XL, decoupled, v5e-16"): the env-host player runs `run_dreamer`'s
exact loop (dreamer_v3.py) with a channel-backed trainer in place of the inline
one, and the learner — a thread on the accelerator mesh in one process, or a
multi-process LEARNER SLICE sharing one DP mesh under ``jax.distributed`` —
consumes ``[G, T, B, ...]`` replay blocks and publishes updated params. Planes
and role split mirror the decoupled PPO/SAC modules (reference
sheeprl/algos/ppo/ppo_decoupled.py:623-666 for the process topology):

- data plane — depth-1 channel of sampled replay blocks; under a multi-process
  slice the block is broadcast and sharded over the slice's ``data`` axis;
- weight plane — the act view ({world_model, actor} — the player's RSSM needs
  the world model) each round; full (params, opt_state, moments) only when the
  player is about to checkpoint, and once more on shutdown (the final-state
  handshake that pairs the sentinel), so off-round checkpoints can be deferred
  rather than dropped.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_optimizers, make_train_phase, run_dreamer
from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
from sheeprl_tpu.parallel.distributed import (
    BroadcastChannel,
    ChannelError,
    coordination_barrier,
    replicated_to_host,
)
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_algorithm


def _act_select(params):
    return {"world_model": params["world_model"], "actor": params["actor"]}


def _full_state_host(params, opt_state, moments_state):
    return (
        replicated_to_host(params),
        replicated_to_host(opt_state),
        replicated_to_host(moments_state),
    )


def _warmup_train_step(fabric, cfg, train_phase, params, opt_state, observation_space, actions_dim, player_world):
    """Compile + first-execute the train program on an all-zeros batch with the
    EXACT shapes/dtypes/shardings of a real round, then discard the outputs.
    Runs before the lockstep channel protocol starts (fenced by the warmup
    coordination barrier), so no channel collective ever spans the multi-minute
    compile — the CPU gloo backend's context rendezvous dies at ~30 s."""
    mesh_size = fabric.world_size
    T = int(cfg.algo.per_rank_sequence_length)
    B = int(cfg.algo.per_rank_batch_size) * int(player_world)
    batch: Dict[str, np.ndarray] = {}
    for k in cfg.algo.cnn_keys.encoder:
        batch[k] = np.zeros((T, B, *observation_space[k].shape), np.uint8)
    for k in cfg.algo.mlp_keys.encoder:
        batch[k] = np.zeros((T, B, *observation_space[k].shape), np.float32)
    batch["actions"] = np.zeros((T, B, int(np.sum(actions_dim))), np.float32)
    for k in ("rewards", "terminated", "truncated", "is_first"):
        batch[k] = np.zeros((T, B, 1), np.float32)
    p, o, m = params, opt_state, init_moments()
    if mesh_size > 1:
        # rule-derived placement: kernels shard over a `model` axis when the mesh
        # has one, everything else replicates — identical to replicate_pytree on
        # the 1-D learner-slice mesh
        p = fabric.shard_params(p)
        o = fabric.shard_params(o)
        m = fabric.replicate_pytree(m)
        batch = jax.device_put(batch, fabric.sharding(None, "data"))
    else:
        # train_step donates its state args; the warmup must burn COPIES or the
        # real params/opt_state handed to _trainer_loop would be invalidated
        p = jax.tree_util.tree_map(jnp.array, p)
        o = jax.tree_util.tree_map(jnp.array, o)
    out = train_phase.train_step(p, o, m, batch, jnp.asarray(0), jax.random.PRNGKey(0))
    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])


def _trainer_loop(
    fabric, cfg, train_phase, params, opt_state, moments_state, data_q, params_q, error,
    telemetry=None, resilience=None,
):
    """Learner role: consume replay blocks, run the fused per-gradient-step program
    over them, publish the act view (full state on request). The shutdown sentinel
    is answered with the FINAL full state so the player can flush a deferred last
    checkpoint.

    ``telemetry``: the learner role's own stream (two-process topology only —
    the threaded trainer shares the player's process, whose telemetry already
    observes it; a second writer would also race the shared timer registry).
    Its step axis is cumulative gradient steps (the only counter the learner
    sees), not policy steps. ``resilience``: likewise the learner PROCESS's peer
    facade (heartbeats, rank-targeted faults, preempt-request publication,
    dead-peer aborts) — the threaded trainer leaves it to the player's monitor."""
    from contextlib import nullcontext

    from sheeprl_tpu.obs import NullTelemetry
    from sheeprl_tpu.resilience import NullResilience
    from sheeprl_tpu.utils.timer import timer

    telemetry = telemetry if telemetry is not None else NullTelemetry()
    resilience = resilience if resilience is not None else NullResilience()
    train_span = timer("Time/train_time") if telemetry.enabled else nullcontext()
    try:
        mesh_size = fabric.world_size
        if mesh_size > 1:
            # same placement as the warmup burn above (shard_params == replicate
            # on a mesh without a model axis)
            params = fabric.shard_params(params)
            opt_state = fabric.shard_params(opt_state)
            moments_state = fabric.replicate_pytree(moments_state)
        last_step = 0
        while True:
            msg = data_q.get()
            if msg is None:
                telemetry.close(last_step)
                params_q.put(_full_state_host(params, opt_state, moments_state))
                return
            data, cum_steps, train_key, want_full, want_metrics = msg
            units = int(data["rewards"].shape[0])
            with train_span:
                if mesh_size > 1:
                    # every learner process holds the full broadcast block; this forms
                    # the global array sharded over the slice mesh (batch axis). The
                    # host G-loop inside train_phase slices global arrays eagerly,
                    # which all slice members execute in lockstep.
                    data = jax.device_put(data, fabric.sharding(None, None, "data"))
                params, opt_state, moments_state, metrics = train_phase(
                    params, opt_state, moments_state, data, jnp.asarray(cum_steps), np.asarray(train_key)
                )
                reply = (
                    replicated_to_host(_act_select(params)),
                    _full_state_host(params, opt_state, moments_state) if want_full else None,
                    replicated_to_host(metrics) if want_metrics else None,
                )
            params_q.put(reply)
            last_step = int(cum_steps) + units
            telemetry.observe_train(units, reply[2])
            telemetry.step(last_step)
            # publishes this rank's preempt request / heartbeat step and raises
            # RankFailureError on a declared-dead peer (never hang on one)
            resilience.step(last_step)
    except BaseException as e:  # surface learner crashes to the player
        error["exc"] = e
        # a crash inside a channel collective leaves the plane desynced: further
        # lockstep puts could hang and bury the traceback
        if not isinstance(e, ChannelError):
            try:
                params_q.put(None)
            except ChannelError:
                pass


class _ChannelTrainer:
    """run_dreamer trainer backed by the data/weight channels (thread or process
    slice). ``defers_checkpoints``: full state exists only at train rounds, so the
    loop postpones off-round checkpoints to the next round (or to close())."""

    defers_checkpoints = True
    # the data plane ships HOST blocks (the two-process channel pickles them); the
    # learner stages onto its own mesh, so the player-side sampler must not device_put
    data_sharding = None

    def __init__(self, *, fabric, cfg, act, train_phase, params, opt_state, moments_state, multi_process, protocol_done):
        self.act = act
        self.error: Dict[str, Any] = {}
        self._last_full: Optional[tuple] = None
        self._protocol_done = protocol_done
        self._thread: Optional[threading.Thread] = None
        self._multi = multi_process
        if multi_process:
            from sheeprl_tpu.resilience import channel_options

            opts = channel_options(cfg)
            self.data_q: Any = BroadcastChannel(src=0, **opts)
            self.params_q: Any = BroadcastChannel(src=1, **opts)
            # the channels are stateful (KV sequence counters): expose them so
            # main()'s crash path releases the learners through the SAME instances
            protocol_done["data_q"] = self.data_q
            protocol_done["params_q"] = self.params_q
            # release point: a learner blocked here exits cleanly if the player
            # dies before the first round (gets None from the crash path)
            self.data_q.put({"player_world_size": fabric.world_size})
            # fence the learners' train-program compile (minutes for big models)
            # out of the lockstep channel protocol: XLA collective contexts have a
            # hard ~30 s rendezvous deadline on the CPU gloo backend, so a channel
            # op must never span a long one-sided compile
            coordination_barrier("dv3_decoupled_warmup")
        else:
            self.data_q = queue.Queue(maxsize=1)
            self.params_q = queue.Queue(maxsize=1)
            self._thread = threading.Thread(
                target=_trainer_loop,
                args=(fabric, cfg, train_phase, params, opt_state, moments_state, self.data_q, self.params_q, self.error),
                daemon=True,
                name="dv3-learner",
            )
            self._thread.start()

    def train(self, data, cum_steps, train_key, want_full_state: bool, want_metrics: bool):
        self.data_q.put((data, int(cum_steps), np.asarray(train_key), bool(want_full_state), bool(want_metrics)))
        msg = self.params_q.get()
        if msg is None:
            if "exc" in self.error:
                raise self.error["exc"]
            raise RuntimeError(
                "the learner crashed mid-run (sent a weight-plane sentinel before "
                "the player finished); see its log"
            )
        act_view_host, full, metrics = msg
        if full is not None:
            self._last_full = full
        return self.act.view(act_view_host), metrics

    def checkpoint_state(self):
        assert self._last_full is not None, (
            "checkpoint_state before any full-state round — run_dreamer only calls "
            "this after a train round with want_full_state=True (defers_checkpoints)"
        )
        return self._last_full

    def sync_tree(self):
        return None  # training state lives with the learner

    def close(self):
        self.data_q.put(None)
        final = self.params_q.get()  # final-state handshake pairs the sentinel
        if self._thread is not None:
            self._thread.join(timeout=60)
        self._protocol_done["done"] = True
        if final is None:
            if "exc" in self.error:
                raise self.error["exc"]
            raise RuntimeError("the learner crashed during shutdown; see its log")
        if "exc" in self.error:
            raise self.error["exc"]
        return final


def _learner_process(fabric, cfg: Dict[str, Any]):
    """One process of the learner slice: rebuild the agent from the shared seed
    (no initial weight transfer — same pattern as decoupled PPO/SAC), then enter
    the data loop. All slice members run this same program in lockstep."""
    import gymnasium as gym

    cfg.env.frame_stack = -1  # match the player's forced setting (run_dreamer)
    env = make_env(cfg, cfg.seed, 0, None, "learner")()
    observation_space = env.observation_space
    action_space = env.action_space
    env.close()
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    key = fabric.seed_everything(cfg.seed)  # player is rank 0: cfg.seed + 0
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
    world_tx, actor_tx, critic_tx, opt_state = build_optimizers(cfg, params)
    from sheeprl_tpu.parallel.sharding import build_state_shardings

    train_phase = make_train_phase(
        agent, cfg, world_tx, actor_tx, critic_tx,
        state_shardings=build_state_shardings(fabric, params, opt_state, init_moments()),
    )
    moments_state = init_moments()

    # the learner's peer facade comes up BEFORE the first blocking channel op:
    # its heartbeat lets the player distinguish "learner is compiling" from
    # "learner is dead" (the warmup compile can take minutes), and its abort
    # check breaks our own waits; the telemetry stream is the learner slice's
    # own (telemetry.learner.jsonl next to the player's — obs/streams.py merges
    # them), one writer per slice
    from sheeprl_tpu.obs import build_role_telemetry
    from sheeprl_tpu.parallel import distributed
    from sheeprl_tpu.resilience import build_resilience, channel_options

    telemetry = build_role_telemetry(
        fabric, cfg, "learner",
        rank=distributed.process_index(),
        leader=distributed.process_index() == 1,
    )
    resilience = build_resilience(fabric, cfg, None, telemetry=telemetry)
    opts = channel_options(cfg)
    data_q, params_q = BroadcastChannel(src=0, **opts), BroadcastChannel(src=1, **opts)
    geometry = data_q.get()
    if geometry is None:  # player failed before the first round
        params_q.put(None)  # pairs the player's cleanup ack-consume
        resilience.finalize()
        return
    try:
        if cfg.checkpoint.resume_from:
            # mirror run_dreamer's resume on the slice (same shared-path assumption
            # as the reference's fabric.load on all ranks)
            from sheeprl_tpu.utils.checkpoint import load_checkpoint

            try:
                state = load_checkpoint(cfg.checkpoint.resume_from)
                params = jax.tree_util.tree_map(jnp.asarray, state["agent"])
                opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
                if state.get("moments") is not None:
                    moments_state = jax.tree_util.tree_map(jnp.asarray, state["moments"])
            except Exception:
                # a load failure must not strand the player: pass the warmup barrier
                # it is waiting at, then surface the crash on the weight plane so its
                # first round raises 'learner crashed mid-run'
                try:
                    coordination_barrier("dv3_decoupled_warmup")
                    params_q.put(None)
                except Exception:
                    pass
                raise
            # the slice only needs params/opt_state/moments; drop the player-side
            # replay buffer the checkpoint carries
            state.pop("rb", None)
        _warmup_train_step(
            fabric, cfg, train_phase, params, opt_state, observation_space, actions_dim,
            geometry["player_world_size"],
        )
        coordination_barrier("dv3_decoupled_warmup")
        error: Dict[str, Any] = {}
        _trainer_loop(
            fabric, cfg, train_phase, params, opt_state, moments_state, data_q, params_q, error,
            telemetry=telemetry, resilience=resilience,
        )
        if "exc" in error:
            # pair the player's final sentinel — unless the crash WAS the channel,
            # whose collectives are desynced and would hang instead of pairing
            if not isinstance(error["exc"], ChannelError):
                try:
                    data_q.get()
                    params_q.put(None)
                except ChannelError:
                    pass
            raise error["exc"]
    finally:
        resilience.finalize()


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    from functools import partial

    from sheeprl_tpu.parallel import distributed

    # Resume: the player path is run_dreamer's own resume (it hands the resumed
    # params/opt_state/moments to the trainer factory); the learner slice loads
    # the checkpoint from its own filesystem in _learner_process.
    multi_process = distributed.process_count() >= 2
    if multi_process:
        # process 0: player on its own devices; processes 1..N-1: learner slice
        # sharing one DP mesh (same topology as decoupled PPO/SAC)
        if distributed.process_index() >= 1:
            fabric.process_group = tuple(range(1, distributed.process_count()))
        fabric.local_mesh = True
        fabric._setup()
        if distributed.process_index() >= 1:
            return _learner_process(fabric, cfg)

    protocol_done = {"done": False}
    try:
        return run_dreamer(
            fabric,
            cfg,
            trainer_factory=partial(
                _ChannelTrainer, multi_process=multi_process, protocol_done=protocol_done
            ),
            # the learner processes never pair the log-dir share collective
            share_log_dir=not multi_process,
        )
    except BaseException as e:
        # best-effort learner release; a ChannelError means the plane itself is
        # desynced and another lockstep collective would hang, not raise
        if multi_process and not protocol_done["done"] and not isinstance(e, ChannelError):
            try:
                from sheeprl_tpu.resilience import channel_options

                # reuse the live (stateful) channel instances when they exist
                opts = channel_options(cfg)
                protocol_done.get("data_q", BroadcastChannel(src=0, **opts)).put(None)
                protocol_done.get("params_q", BroadcastChannel(src=1, **opts)).get()
            except Exception:
                pass
        raise
