"""Dreamer-V3, decoupled actor–learner (MPMD) training.

The reference has NO decoupled Dreamer — this is the BASELINE.md north-star
topology ("DV3 XL, decoupled, v5e-16"): the env-host player runs `run_dreamer`'s
exact loop (dreamer_v3.py) with a channel-backed trainer in place of the inline
one, and the learner — a thread on the accelerator mesh in one process, or a
multi-process LEARNER SLICE sharing one DP mesh under ``jax.distributed`` —
consumes ``[G, T, B, ...]`` replay blocks and publishes updated params. Planes
and role split mirror the decoupled PPO/SAC modules (reference
sheeprl/algos/ppo/ppo_decoupled.py:623-666 for the process topology):

- data plane — depth-1 channel of sampled replay blocks; under a multi-process
  slice the block is broadcast and sharded over the slice's ``data`` axis;
- weight plane — the act view ({world_model, actor} — the player's RSSM needs
  the world model) each round; full (params, opt_state, moments) only when the
  player is about to checkpoint, and once more on shutdown (the final-state
  handshake that pairs the sentinel), so off-round checkpoints can be deferred
  rather than dropped.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import build_optimizers, make_train_phase, run_dreamer
from sheeprl_tpu.resilience import apply_armed_learn_fault
from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
from sheeprl_tpu.parallel.distributed import (
    BroadcastChannel,
    ChannelError,
    coordination_barrier,
    publish_channel_error,
    replicated_to_host,
)
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_algorithm


def _act_select(params):
    return {"world_model": params["world_model"], "actor": params["actor"]}


def _full_state_host(params, opt_state, moments_state):
    return (
        replicated_to_host(params),
        replicated_to_host(opt_state),
        replicated_to_host(moments_state),
    )


def _warmup_train_step(fabric, cfg, train_phase, params, opt_state, observation_space, actions_dim, player_world):
    """Compile + first-execute the train program on an all-zeros batch with the
    EXACT shapes/dtypes/shardings of a real round, then discard the outputs.
    Runs before the lockstep channel protocol starts (fenced by the warmup
    coordination barrier), so no channel collective ever spans the multi-minute
    compile — the CPU gloo backend's context rendezvous dies at ~30 s."""
    mesh_size = fabric.world_size
    T = int(cfg.algo.per_rank_sequence_length)
    B = int(cfg.algo.per_rank_batch_size) * int(player_world)
    batch: Dict[str, np.ndarray] = {}
    for k in cfg.algo.cnn_keys.encoder:
        batch[k] = np.zeros((T, B, *observation_space[k].shape), np.uint8)
    for k in cfg.algo.mlp_keys.encoder:
        batch[k] = np.zeros((T, B, *observation_space[k].shape), np.float32)
    batch["actions"] = np.zeros((T, B, int(np.sum(actions_dim))), np.float32)
    for k in ("rewards", "terminated", "truncated", "is_first"):
        batch[k] = np.zeros((T, B, 1), np.float32)
    p, o, m = params, opt_state, init_moments()
    if mesh_size > 1:
        # rule-derived placement: kernels shard over a `model` axis when the mesh
        # has one, everything else replicates — identical to replicate_pytree on
        # the 1-D learner-slice mesh
        p = fabric.shard_params(p)
        o = fabric.shard_params(o)
        m = fabric.replicate_pytree(m)
        batch = jax.device_put(batch, fabric.sharding(None, "data"))
    else:
        # train_step donates its state args; the warmup must burn COPIES or the
        # real params/opt_state handed to _trainer_loop would be invalidated
        p = jax.tree_util.tree_map(jnp.array, p)
        o = jax.tree_util.tree_map(jnp.array, o)
    out = train_phase.train_step(p, o, m, batch, jnp.asarray(0), jax.random.PRNGKey(0))
    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])


def _trainer_loop(
    fabric, cfg, train_phase, params, opt_state, moments_state, data_q, params_q, error,
    telemetry=None, resilience=None,
):
    """Learner role: consume replay blocks, run the fused per-gradient-step program
    over them, publish the act view (full state on request). The shutdown sentinel
    is answered with the FINAL full state so the player can flush a deferred last
    checkpoint.

    ``telemetry``: the learner role's own stream (two-process topology only —
    the threaded trainer shares the player's process, whose telemetry already
    observes it; a second writer would also race the shared timer registry).
    Its step axis is cumulative gradient steps (the only counter the learner
    sees), not policy steps. ``resilience``: likewise the learner PROCESS's peer
    facade (heartbeats, rank-targeted faults, preempt-request publication,
    dead-peer aborts) — the threaded trainer leaves it to the player's monitor."""
    from contextlib import nullcontext

    from sheeprl_tpu.obs import NullTelemetry
    from sheeprl_tpu.resilience import NullResilience
    from sheeprl_tpu.utils.timer import timer

    telemetry = telemetry if telemetry is not None else NullTelemetry()
    resilience = resilience if resilience is not None else NullResilience()
    train_span = timer("Time/train_time") if telemetry.enabled else nullcontext()
    try:
        mesh_size = fabric.world_size
        if mesh_size > 1:
            # same placement as the warmup burn above (shard_params == replicate
            # on a mesh without a model axis)
            params = fabric.shard_params(params)
            opt_state = fabric.shard_params(opt_state)
            moments_state = fabric.replicate_pytree(moments_state)
        last_step = 0
        while True:
            msg = data_q.get()
            if msg is None:
                telemetry.close(last_step)
                params_q.put(_full_state_host(params, opt_state, moments_state))
                return
            data, cum_steps, train_key, want_full, want_metrics = msg
            units = int(data["rewards"].shape[0])
            with train_span:
                if mesh_size > 1:
                    # every learner process holds the full broadcast block; this forms
                    # the global array sharded over the slice mesh (batch axis). The
                    # host G-loop inside train_phase slices global arrays eagerly,
                    # which all slice members execute in lockstep.
                    data = jax.device_put(data, fabric.sharding(None, None, "data"))
                # one-shot injected learning pathology (resilience.fault=lr_spike
                # targeting the learner process): identity unless armed
                params = apply_armed_learn_fault(params)
                params, opt_state, moments_state, metrics = train_phase(
                    params, opt_state, moments_state, data, jnp.asarray(cum_steps), np.asarray(train_key)
                )
                reply = (
                    replicated_to_host(_act_select(params)),
                    _full_state_host(params, opt_state, moments_state) if want_full else None,
                    replicated_to_host(metrics) if want_metrics else None,
                )
            params_q.put(reply)
            last_step = int(cum_steps) + units
            telemetry.observe_train(units, reply[2])
            # device metrics carry the Learn/ keys; refs only, fetched at window
            telemetry.observe_learn(metrics)
            telemetry.step(last_step)
            # publishes this rank's preempt request / heartbeat step and raises
            # RankFailureError on a declared-dead peer (never hang on one)
            resilience.step(last_step)
    except BaseException as e:  # surface learner crashes to the player
        error["exc"] = e
        # out-of-band marker FIRST: on a non-src learner rank the channel put
        # below is a sequence-counter no-op (BroadcastChannel writes only on
        # src), so the marker is the only signal the blocked peers ever get
        publish_channel_error(f"learner train loop failed: {e!r:.300}")
        # a crash inside a channel collective leaves the plane desynced: further
        # lockstep puts could hang and bury the traceback
        if not isinstance(e, ChannelError):
            try:
                params_q.put(None)
            except ChannelError:
                pass


class _ChannelTrainer:
    """run_dreamer trainer backed by the data/weight channels (thread or process
    slice). ``defers_checkpoints``: full state exists only at train rounds, so the
    loop postpones off-round checkpoints to the next round (or to close())."""

    defers_checkpoints = True
    # the data plane ships HOST blocks (the two-process channel pickles them); the
    # learner stages onto its own mesh, so the player-side sampler must not device_put
    data_sharding = None

    def __init__(self, *, fabric, cfg, act, train_phase, params, opt_state, moments_state, multi_process, protocol_done):
        self.act = act
        self.error: Dict[str, Any] = {}
        self._last_full: Optional[tuple] = None
        self._protocol_done = protocol_done
        self._thread: Optional[threading.Thread] = None
        self._multi = multi_process
        if multi_process:
            from sheeprl_tpu.resilience import channel_options

            opts = channel_options(cfg)
            self.data_q: Any = BroadcastChannel(src=0, **opts)
            self.params_q: Any = BroadcastChannel(src=1, **opts)
            # the channels are stateful (KV sequence counters): expose them so
            # main()'s crash path releases the learners through the SAME instances
            protocol_done["data_q"] = self.data_q
            protocol_done["params_q"] = self.params_q
            # release point: a learner blocked here exits cleanly if the player
            # dies before the first round (gets None from the crash path)
            self.data_q.put({"player_world_size": fabric.world_size})
            # fence the learners' train-program compile (minutes for big models)
            # out of the lockstep channel protocol: XLA collective contexts have a
            # hard ~30 s rendezvous deadline on the CPU gloo backend, so a channel
            # op must never span a long one-sided compile
            coordination_barrier("dv3_decoupled_warmup")
        else:
            self.data_q = queue.Queue(maxsize=1)
            self.params_q = queue.Queue(maxsize=1)
            self._thread = threading.Thread(
                target=_trainer_loop,
                args=(fabric, cfg, train_phase, params, opt_state, moments_state, self.data_q, self.params_q, self.error),
                daemon=True,
                name="dv3-learner",
            )
            self._thread.start()

    def train(self, data, cum_steps, train_key, want_full_state: bool, want_metrics: bool):
        self.data_q.put((data, int(cum_steps), np.asarray(train_key), bool(want_full_state), bool(want_metrics)))
        msg = self.params_q.get()
        if msg is None:
            if "exc" in self.error:
                raise self.error["exc"]
            raise RuntimeError(
                "the learner crashed mid-run (sent a weight-plane sentinel before "
                "the player finished); see its log"
            )
        act_view_host, full, metrics = msg
        if full is not None:
            self._last_full = full
        return self.act.view(act_view_host), metrics

    def checkpoint_state(self):
        assert self._last_full is not None, (
            "checkpoint_state before any full-state round — run_dreamer only calls "
            "this after a train round with want_full_state=True (defers_checkpoints)"
        )
        return self._last_full

    def sync_tree(self):
        return None  # training state lives with the learner

    def close(self):
        self.data_q.put(None)
        final = self.params_q.get()  # final-state handshake pairs the sentinel
        if self._thread is not None:
            self._thread.join(timeout=60)
        self._protocol_done["done"] = True
        if final is None:
            if "exc" in self.error:
                raise self.error["exc"]
            raise RuntimeError("the learner crashed during shutdown; see its log")
        if "exc" in self.error:
            raise self.error["exc"]
        return final


def _learner_process(fabric, cfg: Dict[str, Any]):
    """One process of the learner slice: rebuild the agent from the shared seed
    (no initial weight transfer — same pattern as decoupled PPO/SAC), then enter
    the data loop. All slice members run this same program in lockstep."""
    import gymnasium as gym

    cfg.env.frame_stack = -1  # match the player's forced setting (run_dreamer)
    env = make_env(cfg, cfg.seed, 0, None, "learner")()
    observation_space = env.observation_space
    action_space = env.action_space
    env.close()
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    key = fabric.seed_everything(cfg.seed)  # player is rank 0: cfg.seed + 0
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
    world_tx, actor_tx, critic_tx, opt_state = build_optimizers(cfg, params)
    from sheeprl_tpu.parallel.sharding import build_state_shardings

    train_phase = make_train_phase(
        agent, cfg, world_tx, actor_tx, critic_tx,
        state_shardings=build_state_shardings(fabric, params, opt_state, init_moments()),
    )
    moments_state = init_moments()

    # the learner's peer facade comes up BEFORE the first blocking channel op:
    # its heartbeat lets the player distinguish "learner is compiling" from
    # "learner is dead" (the warmup compile can take minutes), and its abort
    # check breaks our own waits; the telemetry stream is the learner slice's
    # own (telemetry.learner.jsonl next to the player's — obs/streams.py merges
    # them), one writer per slice
    from sheeprl_tpu.obs import build_role_telemetry
    from sheeprl_tpu.parallel import distributed
    from sheeprl_tpu.resilience import build_resilience, channel_options

    telemetry = build_role_telemetry(
        fabric, cfg, "learner",
        rank=distributed.process_index(),
        leader=distributed.process_index() == 1,
    )
    resilience = build_resilience(fabric, cfg, None, telemetry=telemetry)
    opts = channel_options(cfg)
    data_q, params_q = BroadcastChannel(src=0, **opts), BroadcastChannel(src=1, **opts)
    geometry = data_q.get()
    if geometry is None:  # player failed before the first round
        params_q.put(None)  # pairs the player's cleanup ack-consume
        resilience.finalize()
        return
    try:
        if cfg.checkpoint.resume_from:
            # mirror run_dreamer's resume on the slice (same shared-path assumption
            # as the reference's fabric.load on all ranks)
            from sheeprl_tpu.utils.checkpoint import load_checkpoint

            try:
                state = load_checkpoint(cfg.checkpoint.resume_from)
                params = jax.tree_util.tree_map(jnp.asarray, state["agent"])
                opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
                if state.get("moments") is not None:
                    moments_state = jax.tree_util.tree_map(jnp.asarray, state["moments"])
            except Exception as exc:
                # a load failure must not strand the player: pass the warmup barrier
                # it is waiting at, then surface the crash on the weight plane so its
                # first round raises 'learner crashed mid-run'. The put is a real
                # write only on the params src rank; the KV marker covers the rest.
                publish_channel_error(f"checkpoint resume load failed: {exc!r:.300}")
                try:
                    coordination_barrier("dv3_decoupled_warmup")
                    params_q.put(None)
                except Exception:
                    pass
                raise
            # the slice only needs params/opt_state/moments; drop the player-side
            # replay buffer the checkpoint carries
            state.pop("rb", None)
        _warmup_train_step(
            fabric, cfg, train_phase, params, opt_state, observation_space, actions_dim,
            geometry["player_world_size"],
        )
        coordination_barrier("dv3_decoupled_warmup")
        error: Dict[str, Any] = {}
        _trainer_loop(
            fabric, cfg, train_phase, params, opt_state, moments_state, data_q, params_q, error,
            telemetry=telemetry, resilience=resilience,
        )
        if "exc" in error:
            # pair the player's final sentinel — unless the crash WAS the channel,
            # whose collectives are desynced and would hang instead of pairing
            if not isinstance(error["exc"], ChannelError):
                try:
                    data_q.get()
                    params_q.put(None)
                except ChannelError:
                    pass
            raise error["exc"]
    finally:
        resilience.finalize()


# ---------------------------------------------------------------------------------
# buffer.backend=service: K dreamer players ingest into a standalone experience
# plane; one learner process hosts the sequential replay buffer + the SAME fused
# donated train program (sheeprl_tpu/data/service.py, howto/fleet.md). The actor
# ranks run run_dreamer's EXACT loop with three swaps: an ingest-only sampler
# (tiny local ring kept for episode bookkeeping), a trainer whose "train round"
# is a non-blocking weight refresh, and learner-owned checkpoints.
# ---------------------------------------------------------------------------------


class _ServiceActorTrainer:
    """run_dreamer trainer for a service-topology actor: never trains, never
    blocks — each "train round" polls the weight plane and hands back the latest
    act view. The LEARNER owns checkpoints (``external_checkpoints``)."""

    defers_checkpoints = True
    external_checkpoints = True
    data_sharding = None

    def __init__(self, *, fabric, cfg, act, params, writer, subscriber, **_: Any):
        self.act = act
        self._writer = writer
        self._subscriber = subscriber
        self._act_view = act.view(_act_select(params))
        scfg = cfg.buffer.get("service") or {}
        self._done_timeout = float(scfg.get("done_timeout") or 300.0)
        # poll_weights=false freezes the actor on its init weights — the
        # deliberate stale-actor injection the weight_staleness smoke rides
        self._poll_weights = bool(scfg.get("poll_weights", True))

    def train(self, data, cum_steps, train_key, want_full_state: bool, want_metrics: bool):
        payload = self._subscriber.poll() if self._poll_weights else None
        if payload is not None:
            self._act_view = self.act.place(payload["tree"])
            # rows shipped from here on carry this acting version (lineage)
            self._writer.weight_version = int(payload["version"])
        return self._act_view, None

    def checkpoint_state(self):
        raise RuntimeError("service actors never checkpoint (external_checkpoints)")

    def sync_tree(self):
        return None

    def close(self):
        from sheeprl_tpu.resilience import preemption_requested

        self._writer.close(preempted=preemption_requested())
        self._writer.wait_done(timeout_s=self._done_timeout)
        payload = self._subscriber.poll() if self._poll_weights else None
        if payload is not None:
            self._act_view = self.act.place(payload["tree"])
        return None


class _IngestSampler:
    """The replay-sampler surface over an :class:`ExperienceWriter`: ``add``
    mirrors rows into a tiny local bookkeeping ring (run_dreamer's episode
    bookkeeping pokes ``rb.buffer[i]``) and ships them — rank/env-tagged — to
    the service. ``sample`` is never consumed (the service trainer ignores its
    token); the snapshot speaks the sampler telemetry schema, with the writer's
    flow-control block time as the honest ``wait``."""

    is_async = False

    def __init__(self, writer, rb, rank: int, num_envs: int) -> None:
        import threading

        self._writer = writer
        self._rb = rb
        self._rank = int(rank)
        self._num_envs = int(num_envs)
        self.lock = threading.Lock()

    @property
    def buffer(self):
        return self._rb

    def add(self, data, idxes=None, validate_args: bool = False) -> None:
        with self.lock:
            self._rb.add(data, idxes, validate_args=validate_args)
        local = list(idxes) if idxes is not None else list(range(self._num_envs))
        self._writer.add(data, env_ids=[self._rank * self._num_envs + i for i in local])

    def sample(self, n_samples: int):
        return {"__service_rows__": n_samples}

    def telemetry_snapshot(self):
        snap = self._writer.telemetry_snapshot()
        return {
            "is_async": False,
            "wait_seconds": snap["flow_block_seconds"],
            "sample_calls": snap["messages"],
            "units": snap["rows"],
            "occupancy_sum": 0.0,
            "staleness_sum": 0.0,
            "empty_waits": 0,
            "pipeline_len": snap["inflight"],
            "depth": 0,
        }

    def close(self) -> None:
        pass  # EOS is the trainer's close() (it knows the preempt verdict)


def _service_actor(fabric, cfg: Dict[str, Any], layout: Dict[str, Any]):
    from functools import partial

    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
    from sheeprl_tpu.data.service import (
        ActorDataflow,
        ExperienceWriter,
        ServiceError,
        WeightSubscriber,
        coordination_kv,
        service_namespace,
        service_options,
    )
    from sheeprl_tpu.obs import build_role_telemetry, build_telemetry
    from sheeprl_tpu.parallel import distributed

    rank = distributed.process_index()
    actors = int(layout["actors"])
    num_envs = int(cfg.env.num_envs)
    kv = coordination_kv()
    if kv is None:
        raise ServiceError(
            "buffer.backend=service needs the jax.distributed coordination service"
        )
    ns = service_namespace()
    opts = service_options(cfg)
    writer = ExperienceWriter(
        kv,
        ns,
        rank,
        max_inflight=opts["max_inflight"],
        flush_every=opts["flush_every"],
        poll_s=opts["poll_s"],
        timeout_s=opts["timeout_s"],
        abort_check=opts["abort_check"],
    )
    subscriber = WeightSubscriber(
        kv, ns, poll_s=opts["poll_s"], timeout_s=opts["timeout_s"], abort_check=opts["abort_check"]
    )

    # per-actor share of the fleet budget: K actors cover total_steps TOGETHER
    # (the learner counts GLOBAL ingested rows against the global knobs)
    cfg.algo.total_steps = int(cfg.algo.total_steps) // actors
    cfg.algo.learning_starts = int(cfg.algo.learning_starts) // actors
    # the LEARNER owns checkpoints; the loop's blocks are gated off by the
    # trainer's external_checkpoints, these keep the cadence math quiet
    cfg.checkpoint.save_last = False

    def replay_factory(*, cfg, log_dir, obs_keys, state, trainer, world_size):
        # tiny local ring: run_dreamer's episode bookkeeping (crash-restart row
        # rewrite) needs per-env last rows; the real buffer lives with the learner
        rb = EnvIndependentReplayBuffer(
            8,
            n_envs=int(cfg.env.num_envs),
            obs_keys=tuple(obs_keys),
            memmap=False,
            buffer_cls=SequentialReplayBuffer,
        )
        return rb, _IngestSampler(writer, rb, rank, int(cfg.env.num_envs))

    def telemetry_factory(fabric_, cfg_, log_dir_, logger_):
        if rank == 0:
            telemetry = build_telemetry(fabric_, cfg_, log_dir_, logger=logger_)
        else:
            telemetry = build_role_telemetry(fabric_, cfg_, f"actor{rank}", rank=rank)
        # dataflow lineage: actor windows carry weight version/lag + ingestion
        telemetry.attach_dataflow(ActorDataflow(writer, subscriber))
        return telemetry

    return run_dreamer(
        fabric,
        cfg,
        trainer_factory=partial(_ServiceActorTrainer, writer=writer, subscriber=subscriber),
        share_log_dir=False,
        replay_factory=replay_factory,
        telemetry_factory=telemetry_factory,
    )


def _service_learner(fabric, cfg: Dict[str, Any], layout: Dict[str, Any]):
    """The dv3 service learner: sequential replay slots per actor env, the SAME
    fused donated train program (state_shardings pinned), Ratio over globally
    ingested rows, act-view weight publication, learner-owned checkpoints."""
    import time as _time

    import gymnasium as gym

    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
    from sheeprl_tpu.data.prefetch import make_replay_sampler
    from sheeprl_tpu.data.service import (
        ExperienceService,
        LearnerDataflow,
        ServiceError,
        WeightPublisher,
        coordination_kv,
        service_namespace,
        service_options,
    )
    from sheeprl_tpu.obs import build_role_telemetry
    from sheeprl_tpu.parallel import distributed
    from sheeprl_tpu.parallel.sharding import build_state_shardings
    from sheeprl_tpu.resilience import build_resilience
    from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
    from sheeprl_tpu.utils.logger import run_base_dir
    from sheeprl_tpu.utils.timer import timer
    from sheeprl_tpu.utils.utils import Ratio, save_configs

    rank = distributed.process_index()
    actors = int(layout["actors"])
    num_envs = int(cfg.env.num_envs)
    total_envs = actors * num_envs
    policy_steps_per_iter = total_envs

    cfg.env.frame_stack = -1  # match the players' forced setting (run_dreamer)
    env = make_env(cfg, cfg.seed, 0, None, "learner")()
    observation_space = env.observation_space
    action_space = env.action_space
    env.close()
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    key = fabric.seed_everything(cfg.seed)  # rank-0 player init seed
    key, agent_key = jax.random.split(key)
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, agent_key)
    world_tx, actor_tx, critic_tx, opt_state = build_optimizers(cfg, params)
    moments_state = init_moments()

    telemetry = build_role_telemetry(fabric, cfg, "learner", rank=rank, leader=True)
    resilience = build_resilience(fabric, cfg, None, telemetry=telemetry)
    try:
        kv = coordination_kv()
        if kv is None:
            raise ServiceError(
                "buffer.backend=service needs the jax.distributed coordination service"
            )
        ns = service_namespace()
        opts = service_options(cfg)

        state = None
        if cfg.checkpoint.resume_from:
            from sheeprl_tpu.utils.checkpoint import load_checkpoint

            state = load_checkpoint(cfg.checkpoint.resume_from)
        if state is not None:
            params = jax.tree_util.tree_map(jnp.asarray, state["agent"])
            if state.get("opt_state") is not None:
                opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
            if state.get("moments") is not None:
                moments_state = jax.tree_util.tree_map(jnp.asarray, state["moments"])

        train_phase = make_train_phase(
            agent, cfg, world_tx, actor_tx, critic_tx,
            state_shardings=build_state_shardings(fabric, params, opt_state, init_moments()),
        )
        if fabric.num_devices > 1:
            params = fabric.shard_params(params)
            opt_state = fabric.shard_params(opt_state)
            moments_state = fabric.replicate_pytree(moments_state)

        learner_dir = str(run_base_dir(cfg.root_dir, cfg.run_name) / "learner")
        os.makedirs(learner_dir, exist_ok=True)
        save_configs(cfg, learner_dir)

        cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
        mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
        obs_keys = cnn_keys + mlp_keys
        buffer_size = cfg.buffer.size // total_envs if not cfg.dry_run else 8
        rb = EnvIndependentReplayBuffer(
            max(buffer_size, 1),
            n_envs=total_envs,
            obs_keys=obs_keys,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(learner_dir, "memmap_buffer", f"rank_{rank}"),
            buffer_cls=SequentialReplayBuffer,
        )
        rows_base = 0
        if state is not None and "rb" in state:
            rb = state["rb"]
        if state is not None:
            rows_base = int(state.get("service_rows") or 0)

        seq_len = int(cfg.algo.per_rank_sequence_length)
        sampler = make_replay_sampler(
            rb,
            cfg.buffer.get("prefetch"),
            sample_kwargs=dict(
                batch_size=cfg.algo.per_rank_batch_size * fabric.world_size,
                sequence_length=seq_len,
            ),
            uint8_keys=cnn_keys,
            sharding=fabric.sharding(None, None, "data") if fabric.num_devices > 1 else None,
            name="dv3-service-prefetch",
        )
        telemetry.attach_sampler(sampler)

        service = ExperienceService(
            rb,
            kv,
            ns,
            layout["actor_ranks"],
            lock=sampler.lock,
            poll_s=opts["poll_s"],
            env_ids_of=lambda r: list(range(r * num_envs, (r + 1) * num_envs)),
            validate_args=bool(cfg.buffer.validate_args),
        ).start()
        publisher = WeightPublisher(kv, ns)
        publish_every = max(int((cfg.buffer.get("service") or {}).get("publish_every") or 1), 1)
        # dataflow lineage: learner windows carry per-actor weight lag, the
        # sampled-row age distribution and ingest latency from the service
        telemetry.attach_dataflow(LearnerDataflow(service, publisher))
        publisher.publish(replicated_to_host(_act_select(params)))

        ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
        if state is not None and "ratio" in state:
            ratio.load_state_dict(state["ratio"])
        learning_starts = int(cfg.algo.learning_starts) if not cfg.dry_run else 0
        if state is not None and "rb" not in state:
            learning_starts += rows_base
        prefill_rows = max(learning_starts - policy_steps_per_iter, 0)
        checkpoint_every = int(cfg.checkpoint.every)
        last_checkpoint = rows_base
        window_every = int(
            (cfg.metric.get("telemetry") or {}).get("every") or cfg.metric.log_every
        )
        last_service_event = rows_base
        cum_gsteps = 0
        rounds = 0
        key = jax.random.PRNGKey(cfg.seed + 1)
        preempted = False

        def sequences_ready() -> bool:
            # every env slot must hold at least one full training sequence before
            # the cross-slot sampler can be consulted
            return all(b.full or b._pos > seq_len for b in rb.buffer)

        def checkpoint(rows: int, *, is_preempt: bool) -> None:
            ckpt_state = {
                "agent": replicated_to_host(params),
                "opt_state": replicated_to_host(opt_state),
                "moments": replicated_to_host(moments_state),
                "ratio": ratio.state_dict(),
                "iter_num": rows // policy_steps_per_iter,
                "batch_size": cfg.algo.per_rank_batch_size * fabric.world_size,
                "service_rows": rows,
                "last_log": 0,
                "last_checkpoint": rows,
            }
            ckpt_path = os.path.join(learner_dir, "checkpoint", f"ckpt_{rows}_{rank}.ckpt")
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_player",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, rows, preempted=is_preempt)

        while True:
            service.raise_pending()
            rows = rows_base + service.rows_total
            preempted = resilience.preempt_requested()
            eos = service.eos_all()
            warm = rows >= learning_starts and rows > 0 and sequences_ready()
            grant = ratio(max(rows - prefill_rows, 0)) if warm else 0
            if grant > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(grant)
                    key, train_key = jax.random.split(key)
                    params = apply_armed_learn_fault(params)
                    params, opt_state, moments_state, metrics = train_phase(
                        params, opt_state, moments_state, data,
                        jnp.asarray(cum_gsteps), np.asarray(train_key),
                    )
                cum_gsteps += grant
                rounds += 1
                telemetry.observe_train(grant, metrics)
                telemetry.observe_learn(metrics)
                if rounds % publish_every == 0:
                    publisher.publish(replicated_to_host(_act_select(params)))
            elif not eos:
                _time.sleep(opts["poll_s"])
            telemetry.step(rows)
            resilience.step(rows)
            if rows - last_service_event >= window_every:
                last_service_event = rows
                telemetry.emit_event(
                    "service",
                    step=rows,
                    role="learner",
                    gradient_steps=cum_gsteps,
                    weight_version=publisher.version,
                    **service.telemetry_snapshot(),
                )
            if checkpoint_every > 0 and rows - last_checkpoint >= checkpoint_every:
                last_checkpoint = rows
                checkpoint(rows, is_preempt=False)
            if preempted or (eos and grant == 0):
                break

        rows = rows_base + service.rows_total
        if preempted or cfg.checkpoint.save_last or cfg.dry_run:
            checkpoint(rows, is_preempt=preempted or service.eos_preempted())
        publisher.publish(replicated_to_host(_act_select(params)), final=True)
        telemetry.emit_event(
            "service",
            step=rows,
            role="learner",
            gradient_steps=cum_gsteps,
            weight_version=publisher.version,
            **service.telemetry_snapshot(),
        )
        service.mark_done()
        sampler.close()
        service.stop()
        wait_for_checkpoint()
        telemetry.close(rows)
    finally:
        resilience.finalize()


def _service_main(fabric, cfg: Dict[str, Any]):
    from sheeprl_tpu.data.service import service_layout
    from sheeprl_tpu.parallel import distributed

    layout = service_layout(cfg)
    if layout["learners"] != 1:
        raise ValueError(
            f"buffer.backend=service currently takes exactly ONE learner process "
            f"(got {layout['learners']}) — multi-process learner slices ride "
            "buffer.backend=local's channel topology"
        )
    rank = distributed.process_index()
    if rank >= layout["actors"]:
        fabric.process_group = layout["learner_ranks"]
    fabric.local_mesh = True
    fabric._setup()
    if rank >= layout["actors"]:
        return _service_learner(fabric, cfg, layout)
    return _service_actor(fabric, cfg, layout)


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    from functools import partial

    from sheeprl_tpu.parallel import distributed

    if str(cfg.buffer.get("backend", "local")) == "service":
        # standalone experience plane: K dreamer players + 1 learner process
        # (raises with an actionable message on a single-process launch)
        return _service_main(fabric, cfg)

    # Resume: the player path is run_dreamer's own resume (it hands the resumed
    # params/opt_state/moments to the trainer factory); the learner slice loads
    # the checkpoint from its own filesystem in _learner_process.
    multi_process = distributed.process_count() >= 2
    if multi_process:
        # process 0: player on its own devices; processes 1..N-1: learner slice
        # sharing one DP mesh (same topology as decoupled PPO/SAC)
        if distributed.process_index() >= 1:
            fabric.process_group = tuple(range(1, distributed.process_count()))
        fabric.local_mesh = True
        fabric._setup()
        if distributed.process_index() >= 1:
            return _learner_process(fabric, cfg)

    protocol_done = {"done": False}
    try:
        return run_dreamer(
            fabric,
            cfg,
            trainer_factory=partial(
                _ChannelTrainer, multi_process=multi_process, protocol_done=protocol_done
            ),
            # the learner processes never pair the log-dir share collective
            share_log_dir=not multi_process,
        )
    except BaseException as e:
        # best-effort learner release; a ChannelError means the plane itself is
        # desynced and another lockstep collective would hang, not raise
        if multi_process and not protocol_done["done"] and not isinstance(e, ChannelError):
            try:
                from sheeprl_tpu.resilience import channel_options

                # reuse the live (stateful) channel instances when they exist
                opts = channel_options(cfg)
                protocol_done.get("data_q", BroadcastChannel(src=0, **opts)).put(None)
                protocol_done.get("params_q", BroadcastChannel(src=1, **opts)).get()
            except Exception:
                pass
        raise
