"""Dreamer-V3 agent, Flax/JAX-native.

Capability parity with the reference agent (sheeprl/algos/dreamer_v3/agent.py:
CNNEncoder:42, MLPEncoder:103, CNNDecoder:154, MLPDecoder:231, RecurrentModel:285,
RSSM:344, PlayerDV3:596, Actor:694, build_agent:937) redesigned for the TPU:

- the RSSM is a set of small Flax modules plus *pure scan functions*
  (`dynamic_scan`, `imagination_scan`) so the whole sequence unroll is one
  ``lax.scan`` inside a jitted program — the reference pays a Python loop with a
  GRU-cell call per timestep (dreamer_v3.py:86-97);
- images flow NHWC inside the conv stacks (MXU-friendly) while the framework-facing
  arrays stay channel-first like the buffers;
- Hafner initialization (reference utils.py:143-180) maps exactly onto
  ``variance_scaling(1.0, "fan_avg", "truncated_normal")`` / ``(scale, "fan_avg",
  "uniform")`` initializers;
- the agent/player weight-tying dance (agent.py:1237-1260) disappears: one params
  pytree serves the jitted `player_step` and the jitted train program.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import LayerNormGRUCell, resolve_activation
from sheeprl_tpu.ops.conv import FastConv2x
from sheeprl_tpu.ops.deconv import FusedConvTranspose4x4S2
from sheeprl_tpu.utils.utils import symlog

# Hafner init: trunc-normal with variance 1/fan_avg and the 0.8796... correction —
# identical math to reference init_weights (dreamer_v3/utils.py:143-168)
hafner_init = nn.initializers.variance_scaling(1.0, "fan_avg", "truncated_normal")


def uniform_init(scale: float) -> Callable:
    """Reference uniform_init_weights (dreamer_v3/utils.py:170-180): U(-l, l) with
    l = sqrt(3 * scale / fan_avg); scale 0 → zeros."""
    if scale == 0.0:
        return nn.initializers.zeros
    return nn.initializers.variance_scaling(scale, "fan_avg", "uniform")


class DenseStack(nn.Module):
    """[Dense(no bias) → LayerNorm → act] × n — the Dreamer-V3 MLP block."""

    units: int
    n_layers: int
    activation: Any = "silu"
    eps: float = 1e-3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = resolve_activation(self.activation)
        x = x.astype(self.dtype)
        for _ in range(self.n_layers):
            x = nn.Dense(self.units, use_bias=False, kernel_init=hafner_init, dtype=self.dtype)(x)
            x = nn.LayerNorm(epsilon=self.eps, dtype=self.dtype)(x)
            x = act(x)
        return x


class MLPHead(nn.Module):
    """DenseStack + linear head — representation/transition/reward/continue/critic."""

    units: int
    n_layers: int
    output_dim: int
    activation: Any = "silu"
    eps: float = 1e-3
    head_init_scale: Optional[float] = None  # None → hafner trunc-normal
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.units, self.n_layers, self.activation, self.eps, self.dtype)(x)
        init = hafner_init if self.head_init_scale is None else uniform_init(self.head_init_scale)
        return nn.Dense(self.output_dim, kernel_init=init, dtype=self.dtype)(x)


class CNNEncoder(nn.Module):
    """4-stage stride-2 conv encoder, 64x64 → 4x4 (reference agent.py:42-100).
    Inputs are channel-first [..., C, H, W]; convs run NHWC."""

    keys: Sequence[str]
    channels_multiplier: int
    stages: int = 4
    activation: Any = "silu"
    eps: float = 1e-3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        act = resolve_activation(self.activation)
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        lead = x.shape[:-3]
        x = x.reshape(-1, *x.shape[-3:])
        x = jnp.moveaxis(x, -3, -1).astype(self.dtype)  # NCHW -> NHWC
        for i in range(self.stages):
            # CPU fast-gradient stride-2 conv (ops/conv.py; pad-1 folds into the
            # pre-pad); explicit name keeps nn.Conv's parameter tree. TPU keeps
            # the native MXU conv.
            x = FastConv2x(
                features=(2**i) * self.channels_multiplier,
                kernel_size=4,
                padding=1,
                use_bias=False,
                kernel_init=hafner_init,
                dtype=self.dtype,
                name=f"Conv_{i}",
            )(x)
            x = nn.LayerNorm(epsilon=self.eps, dtype=self.dtype)(x)
            x = act(x)
        return x.reshape(*lead, -1)


class MLPEncoder(nn.Module):
    """Vector encoder with optional symlog input squashing (reference agent.py:103-151)."""

    keys: Sequence[str]
    mlp_layers: int = 4
    dense_units: int = 512
    activation: Any = "silu"
    eps: float = 1e-3
    symlog_inputs: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate(
            [symlog(obs[k]) if self.symlog_inputs else obs[k] for k in self.keys], axis=-1
        )
        return DenseStack(self.dense_units, self.mlp_layers, self.activation, self.eps, self.dtype)(x)


class Encoder(nn.Module):
    """Fused cnn+mlp encoder over the obs dict (reference MultiEncoder usage)."""

    cnn_encoder: Optional[CNNEncoder]
    mlp_encoder: Optional[MLPEncoder]

    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs))
        return jnp.concatenate(outs, axis=-1)


class CNNDecoder(nn.Module):
    """Inverse of CNNEncoder: latent → 4x4 → stride-2 deconv stages → channel-first
    images per key (reference agent.py:154-228)."""

    keys: Sequence[str]
    output_channels: Sequence[int]
    channels_multiplier: int
    image_size: Tuple[int, int]
    stages: int = 4
    activation: Any = "silu"
    eps: float = 1e-3
    hafner_heads: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        act = resolve_activation(self.activation)
        spatial = self.image_size[0] // (2**self.stages)
        top_channels = (2 ** (self.stages - 1)) * self.channels_multiplier
        x = nn.Dense(
            top_channels * spatial * spatial, kernel_init=hafner_init, dtype=self.dtype
        )(latent)
        lead = x.shape[:-1]
        x = x.reshape(-1, spatial, spatial, top_channels)
        # FusedConvTranspose4x4S2 == nn.ConvTranspose(k=4, s=2, SAME) exactly
        # (ops/deconv.py; parity-tested), in the phase-decomposed form XLA:CPU runs
        # ~3x faster; explicit names keep the nn.ConvTranspose param tree, so
        # checkpoints are unaffected.
        for i in range(self.stages - 1):
            x = FusedConvTranspose4x4S2(
                (2 ** (self.stages - 2 - i)) * self.channels_multiplier,
                use_bias=False,
                kernel_init=hafner_init,
                dtype=self.dtype,
                name=f"ConvTranspose_{i}",
            )(x)
            x = nn.LayerNorm(epsilon=self.eps, dtype=self.dtype)(x)
            x = act(x)
        x = FusedConvTranspose4x4S2(
            sum(self.output_channels),
            kernel_init=uniform_init(1.0) if self.hafner_heads else hafner_init,
            dtype=self.dtype,
            name=f"ConvTranspose_{self.stages - 1}",
        )(x)
        x = jnp.moveaxis(x, -1, -3)  # NHWC -> NCHW
        x = x.reshape(*lead, *x.shape[-3:])
        splits = np.cumsum(self.output_channels)[:-1].tolist()
        return {k: v for k, v in zip(self.keys, jnp.split(x, splits, axis=-3))}


class MLPDecoder(nn.Module):
    """Inverse of MLPEncoder: shared stack + one linear head per key
    (reference agent.py:231-282)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    mlp_layers: int = 4
    dense_units: int = 512
    activation: Any = "silu"
    eps: float = 1e-3
    hafner_heads: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        x = DenseStack(self.dense_units, self.mlp_layers, self.activation, self.eps, self.dtype)(latent)
        init = uniform_init(1.0) if self.hafner_heads else hafner_init
        return {
            k: nn.Dense(dim, kernel_init=init, dtype=self.dtype)(x)
            for k, dim in zip(self.keys, self.output_dims)
        }


class Decoder(nn.Module):
    cnn_decoder: Optional[CNNDecoder]
    mlp_decoder: Optional[MLPDecoder]

    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latent))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent))
        return out


class RecurrentModel(nn.Module):
    """MLP input projection + layer-norm GRU cell (reference agent.py:285-341)."""

    recurrent_state_size: int
    dense_units: int
    activation: Any = "silu"
    eps: float = 1e-3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, h: jax.Array) -> jax.Array:
        feat = DenseStack(self.dense_units, 1, self.activation, self.eps, self.dtype)(x)
        return LayerNormGRUCell(
            hidden_size=self.recurrent_state_size,
            bias=False,
            layer_norm=True,
            layer_norm_eps=self.eps,
            kernel_init=hafner_init,
            dtype=self.dtype,
        )(h, feat)


class Actor(nn.Module):
    """Dreamer-V3 policy head (reference agent.py:694-884): DenseStack backbone, one
    logits head per discrete action dim (unimix-smoothed), or a single
    mean/std head for continuous control. Returns the *raw head outputs*; sampling
    and distribution math live in pure functions below so they can take PRNG keys."""

    actions_dim: Sequence[int]
    is_continuous: bool
    dense_units: int = 1024
    mlp_layers: int = 5
    activation: Any = "silu"
    eps: float = 1e-3
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, state: jax.Array) -> List[jax.Array]:
        x = DenseStack(self.dense_units, self.mlp_layers, self.activation, self.eps, self.dtype)(state)
        if self.is_continuous:
            return [nn.Dense(int(np.sum(self.actions_dim)) * 2, kernel_init=uniform_init(1.0), dtype=self.dtype)(x)]
        return [
            nn.Dense(dim, kernel_init=uniform_init(1.0), dtype=self.dtype)(x)
            for dim in self.actions_dim
        ]


class MinedojoActor(Actor):
    """Marker subclass selecting MineDojo action masking (reference agent.py:850-935).

    Parameters and forward pass are identical to ``Actor`` — the masking is
    sampling-time logic applied to the head logits (``apply_minedojo_masks`` below),
    driven by the ``mask_*`` observation keys, so it lives in the pure sampling path
    rather than the module."""


# MineDojo functional-action ids whose argument heads are conditionally masked
# (reference agent.py:908-925: 15=craft, 16/17=equip/place, 18=destroy)
_MINEDOJO_CRAFT_ACTION = 15
_MINEDOJO_EQUIP_PLACE_ACTIONS = (16, 17)
_MINEDOJO_DESTROY_ACTION = 18
MINEDOJO_MASK_KEYS = ("mask_action_type", "mask_craft_smelt", "mask_destroy", "mask_equip_place")


def mask_minedojo_head(
    head_idx: int,
    logits: jax.Array,
    mask: Dict[str, jax.Array],
    functional_action: Optional[jax.Array] = None,
) -> jax.Array:
    """Mask one MineDojo actor head's logits with the env-provided validity masks.

    Head 0 (action type) is masked unconditionally; head 1 (craft argument) only
    where the sampled functional action is craft; head 2 (equip/place/destroy
    argument) per the sampled functional action. The reference does the conditional
    part with a per-(t, b) python loop (agent.py:911-925); here it is a vectorized
    ``jnp.where`` over the whole batch. ``functional_action`` (int ids, shape [...])
    is the argmax of the freshly-sampled head-0 one-hot."""
    neg_inf = jnp.asarray(-1e9, logits.dtype)
    if head_idx == 0:
        return jnp.where(mask["mask_action_type"].astype(bool), logits, neg_inf)
    if functional_action is None:
        return logits
    if head_idx == 1 and "mask_craft_smelt" in mask:
        is_craft = (functional_action == _MINEDOJO_CRAFT_ACTION)[..., None]
        invalid = jnp.logical_not(mask["mask_craft_smelt"].astype(bool))
        return jnp.where(jnp.logical_and(is_craft, invalid), neg_inf, logits)
    if head_idx == 2 and "mask_equip_place" in mask and "mask_destroy" in mask:
        is_equip_place = jnp.isin(
            functional_action, jnp.asarray(_MINEDOJO_EQUIP_PLACE_ACTIONS)
        )[..., None]
        is_destroy = (functional_action == _MINEDOJO_DESTROY_ACTION)[..., None]
        invalid_ep = jnp.logical_not(mask["mask_equip_place"].astype(bool))
        invalid_d = jnp.logical_not(mask["mask_destroy"].astype(bool))
        logits = jnp.where(jnp.logical_and(is_equip_place, invalid_ep), neg_inf, logits)
        return jnp.where(jnp.logical_and(is_destroy, invalid_d), neg_inf, logits)
    return logits


# ---------------------------------------------------------------------------------
# pure stochastic-state math
# ---------------------------------------------------------------------------------
def unimix_logits(logits: jax.Array, discrete: int, unimix: float) -> jax.Array:
    """1% uniform mixing of categorical probs (reference RSSM._uniform_mix,
    agent.py:447-459). Takes and returns flat [..., S*D] logits."""
    logits = logits.reshape(*logits.shape[:-1], -1, discrete)
    if unimix > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        uniform = jnp.ones_like(probs) / discrete
        probs = (1 - unimix) * probs + unimix * uniform
        logits = jnp.log(probs)
    return logits.reshape(*logits.shape[:-2], -1)


def stochastic_state(
    logits: jax.Array, discrete: int, key: Optional[jax.Array] = None, sample: bool = True
) -> jax.Array:
    """Straight-through sample (or mode) of the [..., S, D] categorical stack
    (reference dreamer_v2/utils.py:44-61). Returns flat [..., S*D]."""
    shaped = logits.reshape(*logits.shape[:-1], -1, discrete)
    if sample:
        idx = jax.random.categorical(key, shaped, axis=-1)
        onehot = jax.nn.one_hot(idx, discrete, dtype=shaped.dtype)
        probs = jax.nn.softmax(shaped, axis=-1)
        out = jax.lax.stop_gradient(onehot) + probs - jax.lax.stop_gradient(probs)
    else:
        idx = jnp.argmax(shaped, axis=-1)
        out = jax.nn.one_hot(idx, discrete, dtype=shaped.dtype)
    return out.reshape(*out.shape[:-2], -1)


def categorical_kl(post_logits: jax.Array, prior_logits: jax.Array, discrete: int) -> jax.Array:
    """KL( Cat(post) || Cat(prior) ) summed over the stochastic-variable axis;
    flat [..., S*D] logits in, [...] out."""
    post = post_logits.reshape(*post_logits.shape[:-1], -1, discrete)
    prior = prior_logits.reshape(*prior_logits.shape[:-1], -1, discrete)
    post_lp = jax.nn.log_softmax(post, axis=-1)
    prior_lp = jax.nn.log_softmax(prior, axis=-1)
    kl = jnp.sum(jnp.exp(post_lp) * (post_lp - prior_lp), axis=-1)
    return kl.sum(axis=-1)


# ---------------------------------------------------------------------------------
# actor distribution math (pure)
# ---------------------------------------------------------------------------------
def actor_sample(
    agent: "DV3Agent",
    pre_dist: List[jax.Array],
    key: jax.Array,
    greedy: bool = False,
    mask: Optional[Dict[str, jax.Array]] = None,
) -> jax.Array:
    """Sample concatenated actions from the raw actor outputs (one-hot blocks for
    discrete dims, clipped tanh-mean scaled-normal for continuous — reference
    Actor.forward, agent.py:790-855). ``mask`` applies MineDojo per-head validity
    masking (reference MinedojoActor.forward, agent.py:884-935): head 0 sampled
    first, its functional action gating the argument heads."""
    cfg = agent.actor_cfg
    if agent.is_continuous:
        mean, std_raw = jnp.split(pre_dist[0], 2, axis=-1)
        mean = jnp.tanh(mean)
        std = (cfg["max_std"] - cfg["min_std"]) * jax.nn.sigmoid(std_raw + cfg["init_std"]) + cfg["min_std"]
        if greedy:
            actions = mean
        else:
            actions = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
        clip = cfg.get("action_clip", 1.0)
        if clip and clip > 0:
            limit = jnp.full_like(actions, clip)
            scale = limit / jnp.maximum(limit, jnp.abs(actions))
            actions = actions * jax.lax.stop_gradient(scale)
        return actions
    keys = jax.random.split(key, len(pre_dist))
    outs = []
    functional_action = None
    for i, logits in enumerate(pre_dist):
        logits = unimix_logits(logits, logits.shape[-1], cfg.get("unimix", 0.01))
        if mask is not None:
            logits = mask_minedojo_head(i, logits, mask, functional_action)
        if greedy:
            idx = jnp.argmax(logits, axis=-1)
            outs.append(jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype))
        else:
            idx = jax.random.categorical(keys[i], logits, axis=-1)
            onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype)
            probs = jax.nn.softmax(logits, axis=-1)
            outs.append(jax.lax.stop_gradient(onehot) + probs - jax.lax.stop_gradient(probs))
        if functional_action is None:
            functional_action = jnp.argmax(outs[0], axis=-1)
    return jnp.concatenate(outs, axis=-1)


def actor_logprob_entropy(
    agent: "DV3Agent", pre_dist: List[jax.Array], actions: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """log-prob of concatenated ``actions`` under the actor heads + total entropy
    (used by the imagination REINFORCE objective). Shapes [..., 1] / [...]."""
    cfg = agent.actor_cfg
    if agent.is_continuous:
        mean, std_raw = jnp.split(pre_dist[0], 2, axis=-1)
        mean = jnp.tanh(mean)
        std = (cfg["max_std"] - cfg["min_std"]) * jax.nn.sigmoid(std_raw + cfg["init_std"]) + cfg["min_std"]
        var = jnp.square(std)
        lp = (-jnp.square(actions - mean) / (2 * var) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)).sum(
            axis=-1, keepdims=True
        )
        ent = (0.5 + 0.5 * jnp.log(2 * jnp.pi) + jnp.log(std)).sum(axis=-1)
        return lp, ent
    splits = np.cumsum(agent.actions_dim)[:-1].tolist()
    blocks = jnp.split(actions, splits, axis=-1)
    lps, ents = [], []
    for logits, act in zip(pre_dist, blocks):
        logits = unimix_logits(logits, logits.shape[-1], cfg.get("unimix", 0.01))
        lp_all = jax.nn.log_softmax(logits, axis=-1)
        lps.append(jnp.sum(lp_all * act, axis=-1))
        ents.append(-jnp.sum(jnp.exp(lp_all) * lp_all, axis=-1))
    return jnp.stack(lps, axis=-1).sum(axis=-1, keepdims=True), jnp.stack(ents, axis=-1).sum(axis=-1)


# ---------------------------------------------------------------------------------
# agent container + scan programs
# ---------------------------------------------------------------------------------
@dataclass
class DV3Agent:
    """All Flax modules plus the pure-scan RSSM programs. ``params`` pytrees are
    threaded explicitly; layout:

    ``{"world_model": {"encoder", "recurrent_model", "representation_model",
    "transition_model", "observation_model", "reward_model", "continue_model",
    "initial_recurrent_state"}, "actor", "critic", "target_critic"}``
    """

    encoder: Encoder
    recurrent_model: RecurrentModel
    representation_model: MLPHead
    transition_model: MLPHead
    observation_model: Decoder
    reward_model: MLPHead
    continue_model: MLPHead
    actor: Actor
    critic: MLPHead
    actions_dim: Sequence[int]
    is_continuous: bool
    stochastic_size: int
    discrete_size: int
    recurrent_state_size: int
    unimix: float
    actor_cfg: Dict[str, Any] = field(default_factory=dict)
    learnable_initial_recurrent_state: bool = True
    decoupled_rssm: bool = False

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size * self.discrete_size

    @property
    def is_minedojo(self) -> bool:
        return isinstance(self.actor, MinedojoActor)

    @property
    def latent_state_size(self) -> int:
        return self.stoch_state_size + self.recurrent_state_size

    # -- rssm primitives -------------------------------------------------------------

    def initial_state(self, wm_params: Dict, batch_shape: Sequence[int]) -> Tuple[jax.Array, jax.Array]:
        """tanh(learnable w) expanded + transition-mode posterior (reference
        RSSM.get_initial_states, agent.py:406-409)."""
        w = wm_params["initial_recurrent_state"]
        if not self.learnable_initial_recurrent_state:
            w = jax.lax.stop_gradient(w)
        h0 = jnp.broadcast_to(jnp.tanh(w), (*batch_shape, self.recurrent_state_size))
        prior_logits = self.transition_model.apply({"params": wm_params["transition_model"]}, h0)
        prior_logits = unimix_logits(prior_logits, self.discrete_size, self.unimix)
        z0 = stochastic_state(prior_logits, self.discrete_size, sample=False)
        return h0, z0

    def _representation(self, wm_params: Dict, h: jax.Array, embedded: jax.Array, key: jax.Array):
        if self.decoupled_rssm:
            # DecoupledRSSM (reference agent.py:501-596): the posterior depends on
            # the embedded observation ALONE — no recurrent-state input
            rep_in = embedded
        else:
            rep_in = jnp.concatenate([h, embedded], axis=-1)
        logits = self.representation_model.apply(
            {"params": wm_params["representation_model"]}, rep_in
        )
        logits = unimix_logits(logits, self.discrete_size, self.unimix)
        return logits, stochastic_state(logits, self.discrete_size, key)

    def _transition(self, wm_params: Dict, h: jax.Array, key: jax.Array):
        logits = self.transition_model.apply({"params": wm_params["transition_model"]}, h)
        logits = unimix_logits(logits, self.discrete_size, self.unimix)
        return logits, stochastic_state(logits, self.discrete_size, key)

    def _recurrent(self, wm_params: Dict, z: jax.Array, a: jax.Array, h: jax.Array) -> jax.Array:
        return self.recurrent_model.apply(
            {"params": wm_params["recurrent_model"]}, jnp.concatenate([z, a], axis=-1), h
        )

    def dynamic_scan(
        self,
        wm_params: Dict,
        embedded: jax.Array,  # [T, B, E]
        actions: jax.Array,  # [T, B, A]
        is_first: jax.Array,  # [T, B, 1]
        key: jax.Array,
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        """Posterior/prior unroll over the sequence — ONE lax.scan replacing the
        reference's per-timestep Python loop (dreamer_v3.py:86-97).

        Returns (recurrent_states, posteriors, posterior_logits, prior_logits), all
        time-major with flattened stochastic states.
        """
        step, init, xs = self._dynamic_scan_pieces(wm_params, embedded, actions, is_first, key)
        _, (hs, zs, post_logits, prior_logits) = jax.lax.scan(step, init, xs)
        return hs, zs, post_logits, prior_logits

    def dynamic_scan_sp(
        self,
        wm_params: Dict,
        embedded: jax.Array,  # [T, B, E], T sharded over the mesh seq axis
        actions: jax.Array,
        is_first: jax.Array,
        key: jax.Array,
        mesh,
        axis: str = "seq",
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        """Sequence-parallel posterior/prior unroll: the long-context variant of
        ``dynamic_scan`` — the TIME axis is sharded over the mesh ``axis`` and the
        carry hops along a ppermute ring, so each device holds only T/S steps of
        inputs and activations (SURVEY §5.7's extension hook; no reference
        counterpart). Numerically identical to ``dynamic_scan`` (parity-tested);
        both run the SAME step body from ``_dynamic_scan_pieces``."""
        from sheeprl_tpu.parallel.sequence import ring_sequence_scan

        step, init, xs = self._dynamic_scan_pieces(wm_params, embedded, actions, is_first, key)
        _, (hs, zs, post_logits, prior_logits) = ring_sequence_scan(step, init, xs, mesh, axis)
        return hs, zs, post_logits, prior_logits

    def _dynamic_scan_pieces(self, wm_params, embedded, actions, is_first, key):
        """The shared RSSM step body + init + per-step inputs consumed by both the
        plain and the sequence-parallel unrolls."""
        T, B = embedded.shape[:2]
        h0, z0 = self.initial_state(wm_params, (B,))
        keys = jax.random.split(key, T)
        # the carry must keep the compute dtype through the whole scan: fp32
        # actions/is_first would promote the bf16 body output back to fp32 and break
        # the carry-type invariant under precision=bf16-*
        actions = actions.astype(embedded.dtype)
        is_first = is_first.astype(embedded.dtype)
        h0, z0 = h0.astype(embedded.dtype), z0.astype(embedded.dtype)
        init = (
            jnp.zeros((B, self.recurrent_state_size), embedded.dtype),
            jnp.zeros((B, self.stoch_state_size), embedded.dtype),
        )

        def _recurrent_prior(h, z_prev, a, first):
            """Shared step prefix: reset masking, recurrent update, unimixed prior."""
            a = (1 - first) * a
            h = (1 - first) * h + first * h0
            z_prev = (1 - first) * z_prev + first * z0
            h = self._recurrent(wm_params, z_prev, a, h)
            prior_logits = self.transition_model.apply({"params": wm_params["transition_model"]}, h)
            return h, unimix_logits(prior_logits, self.discrete_size, self.unimix)

        if self.decoupled_rssm:
            # the posterior is non-recurrent, so the WHOLE sequence's posteriors come
            # from one batched feedforward pass (reference DecoupledRSSM samples the
            # posterior outside the time loop); only the recurrent/prior chain stays
            # sequential
            post_logits_all, zs_all = jax.vmap(
                lambda e, k: self._representation(wm_params, h0, e, k)
            )(embedded, keys)

            def step(carry, inp):
                h, z_prev = carry
                a, z_t, post_logits_t, first = inp
                h, prior_logits = _recurrent_prior(h, z_prev, a, first)
                return (h, z_t), (h, z_t, post_logits_t, prior_logits)

            return step, init, (actions, zs_all, post_logits_all, is_first)

        def step(carry, inp):
            h, z, = carry
            a, e, first, k = inp
            h, prior_logits = _recurrent_prior(h, z, a, first)
            post_logits, z = self._representation(wm_params, h, e, k)
            return (h, z), (h, z, post_logits, prior_logits)

        return step, init, (actions, embedded, is_first, keys)

    def imagination_scan(
        self,
        wm_params: Dict,
        actor_params: Dict,
        z0: jax.Array,  # [N, S*D] flattened start posteriors (stop-gradient'ed)
        h0: jax.Array,  # [N, H]
        key: jax.Array,
        horizon: int,
    ) -> Tuple[jax.Array, jax.Array]:
        """Latent imagination (reference behaviour_learning, dreamer_v3.py:104-158):
        actor acts on stop-gradient latents, dynamics keep gradients flowing so the
        continuous-control pathwise objective works. Returns
        (latents [H+1, N, L], actions [H+1, N, A])."""
        k0, kscan = jax.random.split(key)
        latent0 = jnp.concatenate([z0, h0], axis=-1)
        pre = self.actor.apply({"params": actor_params}, jax.lax.stop_gradient(latent0))
        a0 = actor_sample(self, pre, k0)

        def step(carry, k):
            z, h, a = carry
            h = self._recurrent(wm_params, z, a, h)
            _, z = self._transition(wm_params, h, k)
            latent = jnp.concatenate([z, h], axis=-1)
            k_act = jax.random.fold_in(k, 1)
            pre = self.actor.apply({"params": actor_params}, jax.lax.stop_gradient(latent))
            a = actor_sample(self, pre, k_act)
            return (z, h, a), (latent, a)

        keys = jax.random.split(kscan, horizon)
        _, (latents, actions) = jax.lax.scan(step, (z0, h0, a0), keys)
        latents = jnp.concatenate([latent0[None], latents], axis=0)
        actions = jnp.concatenate([a0[None], actions], axis=0)
        return latents, actions


def build_agent(
    fabric,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    key: jax.Array,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DV3Agent, Dict[str, Any]]:
    """Create the DV3Agent container + initialized params pytree (role of reference
    build_agent, agent.py:937-1260, minus the Fabric/compile/weight-tying dance)."""
    wm_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    dtype = fabric.compute_dtype

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    cnn_stages = int(np.log2(cfg.env.screen_size) - np.log2(4))
    eps = 1e-3

    cnn_encoder = (
        CNNEncoder(
            keys=cnn_keys,
            channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
            stages=cnn_stages,
            activation=cfg.algo.cnn_act,
            eps=eps,
            dtype=dtype,
        )
        if len(cnn_keys) > 0
        else None
    )
    mlp_encoder = (
        MLPEncoder(
            keys=mlp_keys,
            mlp_layers=wm_cfg.encoder.mlp_layers,
            dense_units=wm_cfg.encoder.dense_units,
            activation=cfg.algo.dense_act,
            eps=eps,
            dtype=dtype,
        )
        if len(mlp_keys) > 0
        else None
    )
    encoder = Encoder(cnn_encoder, mlp_encoder)

    stochastic_size = wm_cfg.stochastic_size
    discrete_size = wm_cfg.discrete_size
    stoch_state_size = stochastic_size * discrete_size
    recurrent_state_size = wm_cfg.recurrent_model.recurrent_state_size
    latent_state_size = stoch_state_size + recurrent_state_size

    recurrent_model = RecurrentModel(
        recurrent_state_size=recurrent_state_size,
        dense_units=wm_cfg.recurrent_model.dense_units,
        activation=cfg.algo.dense_act,
        eps=eps,
        dtype=dtype,
    )
    representation_model = MLPHead(
        units=wm_cfg.representation_model.hidden_size,
        n_layers=1,
        output_dim=stoch_state_size,
        activation=wm_cfg.representation_model.dense_act,
        eps=eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    transition_model = MLPHead(
        units=wm_cfg.transition_model.hidden_size,
        n_layers=1,
        output_dim=stoch_state_size,
        activation=wm_cfg.transition_model.dense_act,
        eps=eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    cnn_decoder = (
        CNNDecoder(
            keys=cnn_dec_keys,
            output_channels=[int(np.prod(obs_space[k].shape[:-2])) for k in cnn_dec_keys],
            channels_multiplier=wm_cfg.observation_model.cnn_channels_multiplier,
            image_size=tuple(obs_space[cnn_dec_keys[0]].shape[-2:]),
            stages=cnn_stages,
            activation=cfg.algo.cnn_act,
            eps=eps,
            hafner_heads=cfg.algo.hafner_initialization,
            dtype=dtype,
        )
        if len(cnn_dec_keys) > 0
        else None
    )
    mlp_decoder = (
        MLPDecoder(
            keys=mlp_dec_keys,
            output_dims=[obs_space[k].shape[0] for k in mlp_dec_keys],
            mlp_layers=wm_cfg.observation_model.mlp_layers,
            dense_units=wm_cfg.observation_model.dense_units,
            activation=cfg.algo.dense_act,
            eps=eps,
            hafner_heads=cfg.algo.hafner_initialization,
            dtype=dtype,
        )
        if len(mlp_dec_keys) > 0
        else None
    )
    observation_model = Decoder(cnn_decoder, mlp_decoder)
    reward_model = MLPHead(
        units=wm_cfg.reward_model.dense_units,
        n_layers=wm_cfg.reward_model.mlp_layers,
        output_dim=wm_cfg.reward_model.bins,
        activation=cfg.algo.dense_act,
        eps=eps,
        head_init_scale=0.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    continue_model = MLPHead(
        units=wm_cfg.discount_model.dense_units,
        n_layers=wm_cfg.discount_model.mlp_layers,
        output_dim=1,
        activation=cfg.algo.dense_act,
        eps=eps,
        head_init_scale=1.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )
    cls_path = str(actor_cfg.get("cls") or "")
    if cls_path:
        from sheeprl_tpu.config.instantiate import locate

        actor_cls = locate(cls_path)
    else:
        actor_cls = Actor
    actor = actor_cls(
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        dense_units=actor_cfg.dense_units,
        mlp_layers=actor_cfg.mlp_layers,
        activation=actor_cfg.dense_act,
        eps=eps,
        dtype=dtype,
    )
    critic = MLPHead(
        units=critic_cfg.dense_units,
        n_layers=critic_cfg.mlp_layers,
        output_dim=critic_cfg.bins,
        activation=critic_cfg.dense_act,
        eps=eps,
        head_init_scale=0.0 if cfg.algo.hafner_initialization else None,
        dtype=dtype,
    )

    agent = DV3Agent(
        encoder=encoder,
        recurrent_model=recurrent_model,
        representation_model=representation_model,
        transition_model=transition_model,
        observation_model=observation_model,
        reward_model=reward_model,
        continue_model=continue_model,
        actor=actor,
        critic=critic,
        actions_dim=tuple(actions_dim),
        is_continuous=is_continuous,
        stochastic_size=stochastic_size,
        discrete_size=discrete_size,
        recurrent_state_size=recurrent_state_size,
        unimix=cfg.algo.unimix,
        actor_cfg={
            "init_std": actor_cfg.init_std,
            "min_std": actor_cfg.min_std,
            "max_std": actor_cfg.get("max_std", 1.0),
            "unimix": actor_cfg.get("unimix", cfg.algo.unimix),
            "action_clip": actor_cfg.get("action_clip", 1.0),
        },
        learnable_initial_recurrent_state=wm_cfg.learnable_initial_recurrent_state,
        decoupled_rssm=bool(wm_cfg.get("decoupled_rssm", False)),
    )

    # -- init params -------------------------------------------------------------
    # The whole init is ONE jitted program: eager flax `.init` calls dispatch hundreds
    # of tiny ops, each paying a device round-trip (multi-second setup on a remote
    # TPU); a single traced program pays one compile + one execution.
    act_dim = int(np.sum(actions_dim))

    def _init_all(key):
        keys = jax.random.split(key, 10)
        dummy_obs = {}
        for k in cnn_keys:
            dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
        for k in mlp_keys:
            dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), jnp.float32)
        embed_dim_probe = encoder.init(keys[0], dummy_obs)
        embedded = encoder.apply(embed_dim_probe, dummy_obs)
        h = jnp.zeros((1, recurrent_state_size), jnp.float32)
        z = jnp.zeros((1, stoch_state_size), jnp.float32)
        latent = jnp.zeros((1, latent_state_size), jnp.float32)

        wm_params = {
            "encoder": embed_dim_probe["params"],
            "recurrent_model": recurrent_model.init(
                keys[1], jnp.concatenate([z, jnp.zeros((1, act_dim), jnp.float32)], axis=-1), h
            )["params"],
            "representation_model": representation_model.init(
                keys[2],
                # decoupled RSSM: the posterior head consumes the embedding alone
                embedded
                if wm_cfg.get("decoupled_rssm", False)
                else jnp.concatenate([h, embedded], axis=-1),
            )["params"],
            "transition_model": transition_model.init(keys[3], h)["params"],
            "observation_model": observation_model.init(keys[4], latent)["params"],
            "reward_model": reward_model.init(keys[5], latent)["params"],
            "continue_model": continue_model.init(keys[6], latent)["params"],
            "initial_recurrent_state": jnp.zeros((recurrent_state_size,), jnp.float32),
        }
        actor_params = actor.init(keys[7], latent)["params"]
        critic_params = critic.init(keys[8], latent)["params"]
        return {
            "world_model": wm_params,
            "actor": actor_params,
            "critic": critic_params,
            # explicit copy so critic/target_critic never alias one buffer — the
            # donated train program rejects f(donate(a), donate(a))
            "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
        }

    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
        if getattr(fabric, "model_parallel", False):
            # restored trees land in the same rule-derived shardings a fresh init
            # would get, so the train program compiles identically across resume
            params = fabric.shard_params(params)
    elif getattr(fabric, "model_parallel", False):
        # jit with out_shardings (parallel/sharding.py): every kernel lands
        # directly in its model-axis shard — the full replicated tree never
        # materializes, so a model larger than one chip's HBM still initializes
        from sheeprl_tpu.parallel.sharding import init_sharded

        params = init_sharded(fabric.mesh, _init_all, key)
    else:
        params = jax.jit(_init_all)(key)
    return agent, params


class PlayerDV3:
    """Stateful env-interaction wrapper (reference PlayerDV3, agent.py:596-694): holds
    the per-env carry (previous action, recurrent + stochastic state) and steps all
    envs through one jitted encoder→RSSM→actor program."""

    def __init__(self, agent: DV3Agent, num_envs: int, cnn_keys: Sequence[str], mlp_keys: Sequence[str]):
        self.agent = agent
        self.num_envs = num_envs
        self.cnn_keys = tuple(cnn_keys)
        self.mlp_keys = tuple(mlp_keys)
        self.actions: Optional[jax.Array] = None
        self.recurrent_state: Optional[jax.Array] = None
        self.stochastic_state: Optional[jax.Array] = None

        agent_ref = self.agent

        def _step(params, obs: Dict[str, jax.Array], a, h, z, key, greedy: bool):
            # the PRNG chain advances inside the jitted program: an un-jitted
            # per-step jax.random.split costs ~0.5 ms of host dispatch
            key, k_repr, k_act = jax.random.split(key, 3)
            wm = params["world_model"]
            embedded = agent_ref.encoder.apply({"params": wm["encoder"]}, obs)
            h = agent_ref._recurrent(wm, z, a, h)
            _, z = agent_ref._representation(wm, h, embedded, k_repr)
            latent = jnp.concatenate([z, h], axis=-1)
            pre = agent_ref.actor.apply({"params": params["actor"]}, latent)
            mask = None
            if agent_ref.is_minedojo and "mask_action_type" in obs:
                mask = {k: obs[k] for k in MINEDOJO_MASK_KEYS if k in obs}
            actions = actor_sample(agent_ref, pre, k_act, greedy=greedy, mask=mask)
            return actions, h, z, key

        self._step = jax.jit(_step, static_argnames=("greedy",))

        def _full_init(params, n):
            h0, z0 = agent_ref.initial_state(params["world_model"], (n,))
            act_dim = int(np.sum(agent_ref.actions_dim))
            return jnp.zeros((n, act_dim), jnp.float32), h0, z0

        self._full_init = jax.jit(_full_init, static_argnames=("n",))

        def _masked_reset(params, a, h, z, mask):
            # one fixed-shape program per num_envs: resets are a `where` over a host
            # mask, not per-index eager scatters (each of which pays a dispatch and,
            # for every new index pattern, a fresh compile)
            h0, z0 = agent_ref.initial_state(params["world_model"], (a.shape[0],))
            m = mask[:, None]
            return a * (1.0 - m), jnp.where(m > 0, h0, h), jnp.where(m > 0, z0, z)

        self._masked_reset = jax.jit(_masked_reset)

    def init_states(self, params: Dict, reset_envs: Optional[Sequence[int]] = None) -> None:
        if reset_envs is None or len(reset_envs) == 0 or self.actions is None:
            self.actions, self.recurrent_state, self.stochastic_state = self._full_init(
                params, self.num_envs
            )
        else:
            mask = np.zeros((self.num_envs,), np.float32)
            mask[np.asarray(reset_envs)] = 1.0
            self.actions, self.recurrent_state, self.stochastic_state = self._masked_reset(
                params, self.actions, self.recurrent_state, self.stochastic_state, mask
            )

    def get_actions(self, params: Dict, obs: Dict[str, jax.Array], key: jax.Array, greedy: bool = False):
        """Returns ``(actions, key)`` — the advanced PRNG chain key."""
        actions, self.recurrent_state, self.stochastic_state, key = self._step(
            params, obs, self.actions, self.recurrent_state, self.stochastic_state, key, greedy
        )
        self.actions = actions
        return actions, key
