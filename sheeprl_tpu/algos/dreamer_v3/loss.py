"""Dreamer-V3 world-model loss (reference sheeprl/algos/dreamer_v3/loss.py:9-91).

Pure-functional: takes predicted logits/modes + targets, returns the scalar loss and
its components. KL balancing uses the 0.5/0.1 dynamic/representation split with free
nats, exactly the reference recursion (Eq. 5 of the paper).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.agent import categorical_kl


def reconstruction_loss(
    observation_log_probs: Dict[str, jax.Array],
    reward_log_prob: jax.Array,
    priors_logits: jax.Array,
    posteriors_logits: jax.Array,
    discrete_size: int,
    kl_dynamic: float = 0.5,
    kl_representation: float = 0.1,
    kl_free_nats: float = 1.0,
    kl_regularizer: float = 1.0,
    continue_log_prob: Optional[jax.Array] = None,
    continue_scale_factor: float = 1.0,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (loss, kl, state_loss, reward_loss, observation_loss, continue_loss).

    ``observation_log_probs``/``reward_log_prob``/``continue_log_prob`` are already
    per-element log-probs of shape [T, B]; KL terms are computed here from the
    [T, B, S*D] logits so the stop-gradient balancing stays in one place.
    """
    observation_loss = -sum(observation_log_probs.values())
    reward_loss = -reward_log_prob
    kl = categorical_kl(jax.lax.stop_gradient(posteriors_logits), priors_logits, discrete_size)
    dyn_loss = kl_dynamic * jnp.maximum(kl, kl_free_nats)
    repr_kl = categorical_kl(
        posteriors_logits, jax.lax.stop_gradient(priors_logits), discrete_size
    )
    repr_loss = kl_representation * jnp.maximum(repr_kl, kl_free_nats)
    kl_loss = dyn_loss + repr_loss
    if continue_log_prob is not None:
        continue_loss = continue_scale_factor * -continue_log_prob
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    loss = (kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss).mean()
    return (
        loss,
        kl.mean(),
        kl_loss.mean(),
        reward_loss.mean(),
        observation_loss.mean(),
        continue_loss.mean(),
    )
