"""Dreamer-V3 evaluation entrypoint (reference: sheeprl/algos/dreamer_v3/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym
import jax

from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_agent
from sheeprl_tpu.algos.dreamer_v3.utils import test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["dreamer_v3", "dreamer_v3_decoupled"])
def evaluate(fabric, cfg: Dict[str, Any], state: Dict[str, Any]):
    logdir = cfg.get("log_dir", "logs/evaluation")
    env = make_env(cfg, cfg.seed, 0, logdir, "test")()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    env.close()
    agent, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        jax.random.PRNGKey(cfg.seed),
        state["agent"] if state else None,
    )
    player = PlayerDV3(agent, 1, cfg.algo.cnn_keys.encoder, cfg.algo.mlp_keys.encoder)
    test(player, params, fabric, cfg, logdir, greedy=False)
