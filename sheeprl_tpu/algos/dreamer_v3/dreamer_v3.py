"""Dreamer-V3, coupled training (capability parity with
sheeprl/algos/dreamer_v3/dreamer_v3.py:428-864).

TPU-native structure:
- the whole gradient step — dynamic-learning scan, world-model loss+update,
  imagination scan, actor update, critic update, target-critic EMA, Moments — is ONE
  jitted device program; each iteration's ``per_rank_gradient_steps`` steps run as a
  ``lax.scan`` over the ``[G, T, B, ...]`` replay block (one host→device upload per
  iteration). The reference instead pays a Python loop per gradient step with three
  ``torch.compile`` regions inside (dreamer_v3.py:741-783);
- sequence unrolls are ``lax.scan``s (agent.dynamic_scan / imagination_scan) — the
  reference's per-timestep GRU python loops (dreamer_v3.py:86-97, 148-156);
- under dp the batch axis is sharded over the mesh ``data`` axis: gradient psums and
  the Moments quantiles (reference all_gathers, utils.py:57) come from XLA collectives
  automatically;
- the act path is a jitted encoder→RSSM-step→actor program with an explicit carry
  (PlayerDV3), replacing the reference's stateful module + per-step ``.cpu()`` syncs.
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import (
    DV3Agent,
    PlayerDV3,
    actor_logprob_entropy,
    build_agent,
)
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import init_moments, prepare_obs, test, update_moments
from sheeprl_tpu.analysis.programs import register_fused_program
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.prefetch import make_replay_sampler
from sheeprl_tpu.envs.wrappers import RestartOnException
from sheeprl_tpu.obs import build_telemetry
from sheeprl_tpu.resilience import apply_armed_learn_fault, build_resilience
from sheeprl_tpu.utils import learn_stats
from sheeprl_tpu.utils.checkpoint import wait_for_checkpoint
from sheeprl_tpu.utils.mfu import unit_avals
from sheeprl_tpu.utils.distribution import (
    BernoulliSafeMode,
    Independent,
    MSEDistribution,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import (
    ActPlacement,
    BenchWindow,
    Ratio,
    compute_lambda_values,
    foreach_gradient_step,
    packed_device_get,
    save_configs,
)


def make_train_phase(
    agent: DV3Agent, cfg, world_tx, actor_tx, critic_tx, world_latent_hook=None,
    state_shardings=None,
):
    """Build the jitted multi-gradient-step train program. Returns
    train_phase(params, opt_state, moments_state, data, cum_steps, key).

    ``world_latent_hook(wm_params, latents, key) -> (head_latents, extra_loss,
    extra_metrics)`` lets forks transform the latent the world-model heads consume and
    add loss terms (offline_dreamer's CEM bottleneck); None keeps plain DV3.

    ``state_shardings`` — optional ``(params, opt_state, moments, metrics)``
    out_shardings pytrees (prefixes allowed) pinning the train-state OUTPUT
    placement on a multi-device mesh. Without the pin GSPMD is free to reshard
    state outputs however propagation likes (observed: small actor/critic leaves
    scattered over an 8-device data mesh), which breaks the params-stay-put
    contract the loops and the donation aliasing rely on; with it, outputs land
    exactly where the inputs live (replicated on a 1-D mesh, rule-sharded over
    ``model`` on a 2-D one — ``build_state_shardings``)."""
    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(cfg.algo.mlp_keys.decoder)
    wm_cfg = cfg.algo.world_model
    gamma = float(cfg.algo.gamma)
    lmbda = float(cfg.algo.lmbda)
    horizon = int(cfg.algo.horizon)
    ent_coef = float(cfg.algo.actor.ent_coef)
    discrete_size = agent.discrete_size
    tau = float(cfg.algo.critic.tau)
    target_freq = int(cfg.algo.critic.per_rank_target_network_update_freq)
    moments_kw = dict(
        decay=float(cfg.algo.actor.moments.decay),
        maximum=float(cfg.algo.actor.moments.max),
        percentile_low=float(cfg.algo.actor.moments.percentile.low),
        percentile_high=float(cfg.algo.actor.moments.percentile.high),
    )
    # static clip thresholds for the learn-stats post-clip norms (the txs from
    # build_optimizers chain clip_by_global_norm with exactly these values).
    # learn_on: compile the Learn/* stats only when the telemetry learning
    # plane is on — the off path lowers byte-identically to the pre-plane program
    learn_on = learn_stats.enabled(cfg)
    clips = {
        "world_model": float(cfg.algo.world_model.clip_gradients or 0) or None,
        "actor": float(cfg.algo.actor.clip_gradients or 0) or None,
        "critic": float(cfg.algo.critic.clip_gradients or 0) or None,
    }

    def world_loss_fn(wm_params, batch, key):
        key, hook_key = jax.random.split(jnp.asarray(key))
        batch_obs = {k: batch[k] / 255.0 - 0.5 for k in cnn_keys}
        batch_obs.update({k: batch[k] for k in mlp_keys})
        is_first = batch["is_first"].at[0].set(jnp.ones_like(batch["is_first"][0]))
        # shift: a_t stored with o_t is the action *leaving* o_t; dynamics consume the
        # action that *led to* o_t (reference dreamer_v3.py:219-221)
        actions = jnp.concatenate(
            [jnp.zeros_like(batch["actions"][:1]), batch["actions"][:-1]], axis=0
        )
        embedded = agent.encoder.apply({"params": wm_params["encoder"]}, batch_obs)
        hs, zs, post_logits, prior_logits = agent.dynamic_scan(
            wm_params, embedded, actions, is_first, key
        )
        latents = jnp.concatenate([zs, hs], axis=-1)
        extra_loss, extra_metrics = 0.0, {}
        if world_latent_hook is not None:
            latents, extra_loss, extra_metrics = world_latent_hook(wm_params, latents, hook_key)
        recon = agent.observation_model.apply({"params": wm_params["observation_model"]}, latents)
        obs_lps = {
            k: MSEDistribution(recon[k], dims=len(recon[k].shape[2:])).log_prob(batch_obs[k])
            for k in cnn_dec_keys
        }
        obs_lps.update(
            {
                k: SymlogDistribution(recon[k], dims=len(recon[k].shape[2:])).log_prob(batch_obs[k])
                for k in mlp_dec_keys
            }
        )
        reward_logits = agent.reward_model.apply({"params": wm_params["reward_model"]}, latents)
        reward_lp = TwoHotEncodingDistribution(reward_logits, dims=1).log_prob(batch["rewards"])
        cont_logits = agent.continue_model.apply({"params": wm_params["continue_model"]}, latents)
        cont_lp = Independent(BernoulliSafeMode(logits=cont_logits), 1).log_prob(
            1.0 - batch["terminated"]
        )
        rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
            obs_lps,
            reward_lp,
            prior_logits,
            post_logits,
            discrete_size,
            kl_dynamic=wm_cfg.kl_dynamic,
            kl_representation=wm_cfg.kl_representation,
            kl_free_nats=wm_cfg.kl_free_nats,
            kl_regularizer=wm_cfg.kl_regularizer,
            continue_log_prob=cont_lp,
            continue_scale_factor=wm_cfg.continue_scale_factor,
        )

        def _cat_entropy(logits):
            shaped = logits.reshape(*logits.shape[:-1], -1, discrete_size)
            lp = jax.nn.log_softmax(shaped, axis=-1)
            return -jnp.sum(jnp.exp(lp) * lp, axis=(-2, -1)).mean()

        loss = rec_loss + extra_loss
        metrics = {
            "Loss/world_model_loss": loss,
            "Loss/observation_loss": observation_loss,
            "Loss/reward_loss": reward_loss,
            "Loss/state_loss": state_loss,
            "Loss/continue_loss": continue_loss,
            "State/kl": kl,
            "State/post_entropy": _cat_entropy(jax.lax.stop_gradient(post_logits)),
            "State/prior_entropy": _cat_entropy(jax.lax.stop_gradient(prior_logits)),
        }
        metrics.update(extra_metrics)
        return loss, (zs, hs, metrics)

    def actor_loss_fn(actor_params, params, zs, hs, true_continue, moments_state, key):
        wm = params["world_model"]
        z0 = jax.lax.stop_gradient(zs).reshape(-1, agent.stoch_state_size)
        h0 = jax.lax.stop_gradient(hs).reshape(-1, agent.recurrent_state_size)
        latents, actions = agent.imagination_scan(wm, actor_params, z0, h0, key, horizon)
        predicted_values = TwoHotEncodingDistribution(
            agent.critic.apply({"params": params["critic"]}, latents), dims=1
        ).mean
        predicted_rewards = TwoHotEncodingDistribution(
            agent.reward_model.apply({"params": wm["reward_model"]}, latents), dims=1
        ).mean
        continues = Independent(
            BernoulliSafeMode(logits=agent.continue_model.apply({"params": wm["continue_model"]}, latents)),
            1,
        ).mode
        continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)
        lambda_values = compute_lambda_values(
            predicted_rewards[1:], predicted_values[1:], continues[1:] * gamma, lmbda
        )
        discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, axis=0) / gamma)

        offset, invscale, new_moments = update_moments(moments_state, lambda_values, **moments_kw)
        baseline = predicted_values[:-1]
        normed_lambda = (lambda_values - offset) / invscale
        normed_baseline = (baseline - offset) / invscale
        advantage = normed_lambda - normed_baseline
        pre = agent.actor.apply({"params": actor_params}, jax.lax.stop_gradient(latents))
        lp, ent = actor_logprob_entropy(agent, pre, jax.lax.stop_gradient(actions))
        if agent.is_continuous:
            objective = advantage
        else:
            objective = lp[:-1] * jax.lax.stop_gradient(advantage)
        entropy = ent_coef * ent[..., None]
        policy_loss = -jnp.mean(discount[:-1] * (objective + entropy[:-1]))
        # learn-stats aux (scalars only): imagined-value statistics, the raw
        # (un-normalized) lambda-vs-baseline TD error, policy entropy
        aux_stats = learn_stats.maybe(learn_on, lambda: {
            **learn_stats.value_stats(jax.lax.stop_gradient(predicted_values)),
            **learn_stats.td_quantiles(jax.lax.stop_gradient(lambda_values - baseline)),
            **learn_stats.entropy_stats(jax.lax.stop_gradient(ent)),
        })
        return policy_loss, (latents, lambda_values, discount, new_moments, aux_stats)

    def critic_loss_fn(critic_params, target_params, latents, lambda_values, discount):
        qv_logits = agent.critic.apply({"params": critic_params}, latents[:-1])
        qv = TwoHotEncodingDistribution(qv_logits, dims=1)
        target_values = TwoHotEncodingDistribution(
            agent.critic.apply({"params": target_params}, latents[:-1]), dims=1
        ).mean
        value_loss = -qv.log_prob(jax.lax.stop_gradient(lambda_values))
        value_loss = value_loss - qv.log_prob(jax.lax.stop_gradient(target_values))
        return jnp.mean(value_loss * discount[:-1].squeeze(-1))

    # ONE compiled program per single gradient step, driven by a host loop over the
    # [G, ...] replay block. Two reasons this beats an outer ``lax.scan`` over G:
    # (a) measured 3.6x faster steady-state on XLA CPU — the scan-carried
    # params/opt-state force layout copies and block fusion across the while-loop
    # body; (b) every distinct ``per_rank_gradient_steps`` value the Ratio governor
    # produces would recompile the whole scanned program (~45 s each); the
    # single-step program compiles once for any G.
    # donate_argnums: XLA reuses the params/opt-state/moments buffers in place
    # instead of copying the whole train state every gradient step (all drivers —
    # foreach_gradient_step, the trainers, warmup — rebind to the returned trees,
    # so the invalidated inputs are never read again).
    jit_kwargs = {}
    if state_shardings is not None:
        jit_kwargs["out_shardings"] = tuple(state_shardings)

    @partial(jax.jit, donate_argnums=(0, 1, 2), **jit_kwargs)
    def train_step(params, opt_state, moments_state, batch, cum, k):
        k_world, k_img = jax.random.split(jnp.asarray(k))

        # target-critic EMA before the step (reference dreamer_v3.py:756-761)
        do_ema = (cum % target_freq) == 0
        tau_eff = jnp.where(cum == 0, 1.0, tau)
        params = {
            **params,
            "target_critic": jax.tree_util.tree_map(
                lambda t, c: jnp.where(do_ema, tau_eff * c + (1 - tau_eff) * t, t),
                params["target_critic"],
                params["critic"],
            ),
        }

        (w_loss, (zs, hs, w_metrics)), w_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(
            params["world_model"], batch, k_world
        )
        w_updates, new_wopt = world_tx.update(w_grads, opt_state["world_model"], params["world_model"])
        params = {**params, "world_model": optax.apply_updates(params["world_model"], w_updates)}
        opt_state = {**opt_state, "world_model": new_wopt}

        true_continue = (1 - batch["terminated"]).reshape(-1, 1)
        (a_loss, (latents, lambda_values, discount, new_moments, aux_stats)), a_grads = (
            jax.value_and_grad(actor_loss_fn, has_aux=True)(
                params["actor"], params, zs, hs, true_continue, moments_state, k_img
            )
        )
        a_updates, new_aopt = actor_tx.update(a_grads, opt_state["actor"], params["actor"])
        params = {**params, "actor": optax.apply_updates(params["actor"], a_updates)}
        opt_state = {**opt_state, "actor": new_aopt}
        moments_state = new_moments

        latents_sg = jax.lax.stop_gradient(latents)
        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
            params["critic"], params["target_critic"], latents_sg, lambda_values, discount
        )
        c_updates, new_copt = critic_tx.update(c_grads, opt_state["critic"], params["critic"])
        params = {**params, "critic": optax.apply_updates(params["critic"], c_updates)}
        opt_state = {**opt_state, "critic": new_copt}

        metrics = dict(w_metrics)
        metrics["Loss/policy_loss"] = a_loss
        metrics["Loss/value_loss"] = c_loss
        metrics["Grads/world_model"] = optax.global_norm(w_grads)
        metrics["Grads/actor"] = optax.global_norm(a_grads)
        metrics["Grads/critic"] = optax.global_norm(c_grads)
        # training-health block, riding the metrics dict (the Learn/ prefix is
        # what RunTelemetry.observe_learn extracts — utils/learn_stats.py)
        if learn_on:
            metrics.update(aux_stats)
            metrics.update(
                learn_stats.group_stats(
                    "world_model",
                    grads=w_grads,
                    updates=w_updates,
                    params=params["world_model"],
                    opt_state=new_wopt,
                    clip=clips["world_model"],
                )
            )
            metrics.update(
                learn_stats.group_stats(
                    "actor",
                    grads=a_grads,
                    updates=a_updates,
                    params=params["actor"],
                    opt_state=new_aopt,
                    clip=clips["actor"],
                )
            )
            metrics.update(
                learn_stats.group_stats(
                    "critic",
                    grads=c_grads,
                    updates=c_updates,
                    params=params["critic"],
                    opt_state=new_copt,
                    clip=clips["critic"],
                )
            )
            metrics.update(
                learn_stats.kl_stats(
                    w_metrics["State/kl"],
                    w_metrics["State/post_entropy"],
                    w_metrics["State/prior_entropy"],
                )
            )
            metrics["Learn/loss/world_model"] = w_loss
            metrics["Learn/loss/actor"] = a_loss
            metrics["Learn/loss/critic"] = c_loss
        return params, opt_state, moments_state, metrics

    def train_phase(params, opt_state, moments_state, data, cum_steps, train_key):
        return foreach_gradient_step(
            train_step, (params, opt_state, moments_state), data, train_key, cum_steps
        )

    # the compiled unit, exposed for FLOPs/MFU accounting (utils/mfu.py, bench.py)
    train_phase.train_step = train_step
    return train_phase


def build_optimizers(cfg, params):
    """The three Dreamer optimizers with per-group clipping (reference
    dreamer_v3.py:525-538). ONE construction shared by the coupled loop and the
    decoupled learner: the learner rebuilds training state from the shared seed
    with no weight transfer, so the two must stay bit-identical."""

    def _tx(opt_cfg, clip):
        base = instantiate(opt_cfg)
        if clip is not None and clip > 0:
            return optax.chain(optax.clip_by_global_norm(clip), base)
        return base

    world_tx = _tx(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = _tx(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = _tx(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    opt_state = {
        "world_model": world_tx.init(params["world_model"]),
        "actor": actor_tx.init(params["actor"]),
        "critic": critic_tx.init(params["critic"]),
    }
    return world_tx, actor_tx, critic_tx, opt_state


@register_fused_program(
    "dreamer_v3.train_step",
    min_donated=3,
    doc="fused single-gradient-step Dreamer-V3 world/actor/critic update",
)
def _aot_train_step():
    """Tiny DV3 agent through the loop's own factory (the __graft_entry__
    dryrun recipe at AOT scale)."""
    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.analysis.programs import (
        tiny_dreamer_batch,
        tiny_dreamer_cfg,
        tiny_fabric,
        tiny_obs_space,
    )

    cfg = tiny_dreamer_cfg("dreamer_v3", extra=("algo.world_model.discrete_size=4",))
    fabric = tiny_fabric()
    agent, params = build_agent(fabric, (4,), False, cfg, tiny_obs_space(), jax.random.PRNGKey(0))
    world_tx, actor_tx, critic_tx, opt_state = build_optimizers(cfg, params)
    train_phase = make_train_phase(agent, cfg, world_tx, actor_tx, critic_tx)
    batch = tiny_dreamer_batch(cfg)
    args = (params, opt_state, init_moments(), batch, jnp.asarray(0), np.asarray(jax.random.PRNGKey(1)))
    return train_phase.train_step, args


class _InlineTrainer:
    """Owns the training state and runs the fused train program in-process — the
    coupled path. The decoupled variant ships the replay block over a data channel
    to a learner (thread or process slice) instead and implements this same
    surface (dreamer_v3_decoupled.py), which is the only difference between the
    two training topologies."""

    # a deferring trainer (channel-backed) can only produce full checkpoint state
    # at train rounds; the loop then postpones an off-round checkpoint to the next
    # train round (or to close())
    defers_checkpoints = False

    def __init__(self, *, fabric, cfg, act, train_phase, params, opt_state, moments_state):
        self.fabric = fabric
        self.act = act
        self.train_phase = train_phase
        self.params = params
        self.opt_state = opt_state
        self.moments_state = moments_state
        # the replay sampler stages train blocks with this sharding (off-thread when
        # prefetch is on); a channel trainer keeps it None — its data plane ships
        # host blocks and the learner stages them itself. The guard is TOTAL mesh
        # devices: a data x model mesh needs the batch committed to the mesh
        # (P("data") replicates it over the model axis) even when data extent is 1
        self.data_sharding = fabric.sharding(None, None, "data") if fabric.num_devices > 1 else None

    def train(self, data, cum_steps, train_key, want_full_state: bool, want_metrics: bool):
        """One train round over the ``[G, T, B, ...]`` block (already staged with
        ``data_sharding`` by the replay sampler). Returns
        ``(act_params, host_metrics_or_None)``."""
        # one-shot injected learning pathology (resilience.fault=lr_spike):
        # identity unless the fault armed this iteration
        self.params = apply_armed_learn_fault(self.params)
        self.params, self.opt_state, self.moments_state, metrics = self.train_phase(
            self.params,
            self.opt_state,
            self.moments_state,
            data,
            jnp.asarray(cum_steps),
            np.asarray(train_key),
        )
        # fresh output buffers (never donated), held for the telemetry health
        # guard — which only syncs them at window boundaries, off the hot path
        self.last_metrics = metrics
        host_metrics = packed_device_get(metrics) if want_metrics else None
        return self.act.view(self.params), host_metrics

    def checkpoint_state(self):
        """(agent_params, opt_state, moments) for the checkpoint callback."""
        return self.params, self.opt_state, self.moments_state

    def sync_tree(self):
        """Tree to block on for steady-state bench windows (None = nothing)."""
        return self.params

    def close(self):
        """End-of-run teardown. A channel trainer returns the learner's FINAL full
        state here (paired with the shutdown sentinel) for a deferred last
        checkpoint; inline training has nothing deferred."""
        return None


def run_dreamer(
    fabric,
    cfg: Dict[str, Any],
    *,
    build_agent_fn=None,
    player_cls=None,
    make_train_phase_fn=None,
    test_fn=None,
    trainer_factory=None,
    share_log_dir: bool = True,
    replay_factory=None,
    telemetry_factory=None,
):
    """The full Dreamer-V3 training loop, with the agent/player/train-phase factories
    injectable so forks with the same loop shape (offline_dreamer's CBWM, reference
    offline_dreamer.py:446-866) reuse it instead of copying ~400 lines.
    ``trainer_factory`` swaps the in-process trainer for a channel-backed one — the
    decoupled actor–learner topology (dreamer_v3_decoupled.py) reuses this exact
    loop as its player, passing ``share_log_dir=False`` in the multi-process
    topology: the learner processes never pair the log-dir share collective, so
    issuing it would desync the channel planes.

    ``replay_factory(cfg, log_dir, obs_keys, state, trainer, world_size) ->
    (rb, sampler)`` swaps the replay construction — the experience-service actor
    (``buffer.backend=service``) keeps only a tiny local ring for episode
    bookkeeping and ships rows to the standalone data plane. ``telemetry_factory``
    likewise overrides ``build_telemetry`` (per-actor role streams). A trainer
    advertising ``external_checkpoints = True`` (the service actor's — the
    LEARNER owns checkpoints there) makes this loop skip its checkpoint blocks
    entirely."""
    build_agent_fn = build_agent_fn or build_agent
    player_cls = player_cls or PlayerDV3
    make_train_phase_fn = make_train_phase_fn or make_train_phase
    test_fn = test_fn or test
    rank = fabric.global_rank
    world_size = fabric.world_size

    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    # These arguments cannot be changed (reference dreamer_v3.py:437-440)
    cfg.env.frame_stack = -1
    if 2 ** int(np.log2(cfg.env.screen_size)) != cfg.env.screen_size:
        raise ValueError(f"The screen size must be a power of 2, got: {cfg.env.screen_size}")

    log_dir = get_log_dir(fabric, cfg.root_dir, cfg.run_name, share=share_log_dir)
    logger = get_logger(fabric, cfg, log_dir=log_dir)
    fabric.logger = logger
    if logger is not None:
        logger.log_hyperparams(cfg.as_dict())
    fabric.print(f"Log dir: {log_dir}")
    telemetry = (
        telemetry_factory(fabric, cfg, log_dir, logger)
        if telemetry_factory is not None
        else build_telemetry(fabric, cfg, log_dir, logger=logger)
    )
    resilience = build_resilience(fabric, cfg, log_dir, telemetry=telemetry)

    vectorized_env = gym.vector.SyncVectorEnv if cfg.env.sync_env else gym.vector.AsyncVectorEnv
    num_envs = int(cfg.env.num_envs)
    envs = vectorized_env(
        [
            partial(
                RestartOnException,
                make_env(
                    cfg,
                    cfg.seed + rank * num_envs + i,
                    rank * num_envs,
                    log_dir if rank == 0 else None,
                    "train",
                    vector_env_idx=i,
                ),
            )
            for i in range(num_envs)
        ],
        autoreset_mode=gym.vector.AutoresetMode.SAME_STEP,
    )
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if (
        len(set(cnn_keys).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(mlp_keys).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if len(set(cfg.algo.cnn_keys.decoder) - set(cnn_keys)) > 0:
        raise RuntimeError(
            "The CNN keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.cnn_keys.decoder))}"
        )
    if len(set(cfg.algo.mlp_keys.decoder) - set(mlp_keys)) > 0:
        raise RuntimeError(
            "The MLP keys of the decoder must be contained in the encoder ones. "
            f"Those keys are decoded without being encoded: {list(set(cfg.algo.mlp_keys.decoder))}"
        )
    if cfg.metric.log_level > 0:
        fabric.print("Encoder CNN keys:", cnn_keys)
        fabric.print("Encoder MLP keys:", mlp_keys)
        fabric.print("Decoder CNN keys:", list(cfg.algo.cnn_keys.decoder))
        fabric.print("Decoder MLP keys:", list(cfg.algo.mlp_keys.decoder))
    obs_keys = cnn_keys + mlp_keys

    key = fabric.seed_everything(cfg.seed + rank)
    key, agent_key = jax.random.split(key)
    agent, params = build_agent_fn(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        agent_key,
        state["agent"] if state else None,
    )
    player = player_cls(agent, num_envs, cnn_keys, mlp_keys)

    world_tx, actor_tx, critic_tx, opt_state = build_optimizers(cfg, params)
    if state is not None and "opt_state" in state:
        opt_state = jax.tree_util.tree_map(jnp.asarray, state["opt_state"])
    moments_state = init_moments()
    if state is not None and "moments" in state:
        moments_state = jax.tree_util.tree_map(jnp.asarray, state["moments"])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(cfg.metric.aggregator)

    rb = None
    if replay_factory is None:
        buffer_size = cfg.buffer.size // int(num_envs * world_size) if not cfg.dry_run else 8
        rb = EnvIndependentReplayBuffer(
            buffer_size,
            n_envs=num_envs,
            obs_keys=tuple(obs_keys),
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
            buffer_cls=SequentialReplayBuffer,
        )
        if state is not None and "rb" in state:
            rb = state["rb"]

    from sheeprl_tpu.parallel.sharding import build_state_shardings

    train_phase = make_train_phase_fn(
        agent,
        cfg,
        world_tx,
        actor_tx,
        critic_tx,
        # pin the train state's output placement on any multi-device mesh:
        # replicated on 1-D dp, rule-sharded over `model` on a 2-D mesh
        state_shardings=build_state_shardings(fabric, params, opt_state, moments_state),
    )

    # Act/train device split (shared ActPlacement design, utils/utils.py): with the
    # fabric on an accelerator the per-step player program runs on the host CPU
    # backend — per-dispatch latency to a TPU dwarfs the one-frame forward; the
    # reference pays per-step .cpu() syncs instead (dreamer_v3.py:630-664) — while
    # the fused multi-gradient-step train program runs on the accelerator. Only the
    # player-visible params cross back per train call, as one packed transfer.
    act = ActPlacement(fabric, lambda p: {"world_model": p["world_model"], "actor": p["actor"]})
    act_params = act.view(params)
    key = act.place(key)

    trainer = (trainer_factory or _InlineTrainer)(
        fabric=fabric,
        cfg=cfg,
        act=act,
        train_phase=train_phase,
        params=params,
        opt_state=opt_state,
        moments_state=moments_state,
    )

    # counters (reference dreamer_v3.py:571-597)
    start_iter = (state["iter_num"] // world_size) + 1 if state is not None else 1
    policy_step = state["iter_num"] * num_envs if state is not None else 0
    last_log = state["last_log"] if state is not None else 0
    last_checkpoint = state["last_checkpoint"] if state is not None else 0
    policy_steps_per_iter = int(num_envs * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if state is not None:
        cfg.algo.per_rank_batch_size = state["batch_size"] // world_size
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state is not None and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    # replay hot path: async prefetcher (sampling + sharded staging off-thread) or the
    # exact inline path when buffer.prefetch.enabled=false. Built AFTER the resume
    # block above so a restored batch size shapes the staged units. A
    # replay_factory (the experience-service actor) swaps in its own pair — a
    # tiny bookkeeping ring + an ingest-only sampler facade.
    if replay_factory is not None:
        rb, sampler = replay_factory(
            cfg=cfg,
            log_dir=log_dir,
            obs_keys=obs_keys,
            state=state,
            trainer=trainer,
            world_size=world_size,
        )
    else:
        sampler = make_replay_sampler(
            rb,
            cfg.buffer.get("prefetch"),
            sample_kwargs=dict(
                batch_size=cfg.algo.per_rank_batch_size * world_size,
                sequence_length=cfg.algo.per_rank_sequence_length,
            ),
            uint8_keys=cnn_keys,
            sharding=trainer.data_sharding,
            name="dv3-replay-prefetch",
        )
    telemetry.attach_sampler(sampler)

    if cfg.metric.log_level > 0 and cfg.metric.log_every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The metric.log_every parameter ({cfg.metric.log_every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )
    if cfg.checkpoint.every % policy_steps_per_iter != 0:
        warnings.warn(
            f"The checkpoint.every parameter ({cfg.checkpoint.every}) is not a multiple of the "
            f"policy_steps_per_iter value ({policy_steps_per_iter})."
        )

    # first observation
    step_data: Dict[str, np.ndarray] = {}
    obs = envs.reset(seed=cfg.seed)[0]
    for k in obs_keys:
        step_data[k] = np.asarray(obs[k])[np.newaxis]
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states(act_params)

    cumulative_per_rank_gradient_steps = 0
    train_step = 0
    last_train = 0
    act_dim = int(np.sum(actions_dim))
    pending_ckpt = False

    # Optional steady-state measurement window for bench.py (see bench.py docstring)
    bench = BenchWindow()

    for iter_num in range(start_iter, total_iters + 1):
        bench.maybe_start(policy_step, trainer.sync_tree())
        policy_step += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and state is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    # [num_envs, n_dims] (or [num_envs] for a single Discrete) → one
                    # one-hot block per action dim, env-major
                    per_dim = actions.reshape(num_envs, len(actions_dim)).T
                    actions = np.concatenate(
                        [np.eye(dim, dtype=np.float32)[act] for act, dim in zip(per_dim, actions_dim)],
                        axis=-1,
                    )
            else:
                jobs = prepare_obs(fabric, obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
                actions, key = player.get_actions(act_params, jobs, key)
                actions = np.asarray(actions)
                if is_continuous:
                    real_actions = actions
                else:
                    splits = np.cumsum(actions_dim)[:-1]
                    real_actions = np.stack(
                        [b.argmax(-1) for b in np.split(actions, splits, axis=-1)], axis=-1
                    )

            step_data["actions"] = actions.reshape((1, num_envs, -1)).astype(np.float32)
            sampler.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])
        if "restart_on_exception" in infos:
            # surface the crash-restart (previously invisible): Health/env_restarts
            # gauge + an immediate health event in telemetry.jsonl
            telemetry.observe_env_restart(int(np.sum(infos["restart_on_exception"])))
            # in-place ring-storage rewrite: take the sampler lock so a concurrent
            # prefetch gather never reads a torn episode-boundary row
            with sampler.lock:
                for i, agent_roe in enumerate(infos["restart_on_exception"]):
                    if agent_roe and not dones[i]:
                        sub_rb = rb.buffer[i]
                        last_inserted_idx = (sub_rb._pos - 1) % sub_rb.buffer_size
                        sub_rb["terminated"][last_inserted_idx] = np.zeros_like(
                            sub_rb["terminated"][last_inserted_idx]
                        )
                        sub_rb["truncated"][last_inserted_idx] = np.ones_like(
                            sub_rb["truncated"][last_inserted_idx]
                        )
                        sub_rb["is_first"][last_inserted_idx] = np.zeros_like(
                            sub_rb["is_first"][last_inserted_idx]
                        )
                        step_data["is_first"][:, i] = np.ones_like(step_data["is_first"][:, i])

        ep_info = infos.get("final_info", infos)
        if (cfg.metric.log_level > 0 or telemetry.enabled) and "episode" in ep_info:
            ep = ep_info["episode"]
            mask = ep.get("_r", ep_info.get("_episode", np.ones(num_envs, bool)))
            rews, lens = ep["r"][mask], ep["l"][mask]
            if len(rews) > 0:
                telemetry.observe_episodes(rews, lens)
                if aggregator and not aggregator.disabled:
                    aggregator.update("Rewards/rew_avg", float(np.mean(rews)))
                    aggregator.update("Game/ep_len_avg", float(np.mean(lens)))

        # real next obs of finished episodes (reference dreamer_v3.py:701-708)
        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
        final_obs_arr = infos.get("final_observation", infos.get("final_obs"))
        if final_obs_arr is not None:
            for idx in range(num_envs):
                if final_obs_arr[idx] is not None:
                    for k in obs_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs_arr[idx][k])

        for k in obs_keys:
            step_data[k] = np.asarray(next_obs[k])[np.newaxis]
        obs = next_obs

        rewards = np.asarray(rewards, dtype=np.float32).reshape((1, num_envs, -1))
        step_data["terminated"] = np.asarray(terminated, np.float32).reshape((1, num_envs, -1))
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape((1, num_envs, -1))
        step_data["rewards"] = clip_rewards_fn(rewards)

        dones_idxes = dones.nonzero()[0].tolist()
        reset_envs = len(dones_idxes)
        if reset_envs > 0:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = (real_next_obs[k][dones_idxes])[np.newaxis]
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, reset_envs, act_dim), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            sampler.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            # the reset rows restart the episode in the *live* step_data
            step_data["rewards"][:, dones_idxes] = 0.0
            step_data["terminated"][:, dones_idxes] = 0.0
            step_data["truncated"][:, dones_idxes] = 0.0
            step_data["is_first"][:, dones_idxes] = 1.0
            player.init_states(act_params, dones_idxes)

        # checkpoint due? (computed BEFORE the train round so a channel trainer can
        # ship the full state with it; a deferring trainer postpones off-round
        # checkpoints to the next train round). A preemption forces an
        # out-of-cadence emergency checkpoint through the same path; the flag is
        # snapshotted once per iteration so the save and the loop-exit break can
        # never disagree about it.
        preempted = resilience.preempt_requested()
        pending_ckpt = pending_ckpt or preempted or (
            (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        )
        trained_this_iter = False

        # train
        if iter_num >= learning_starts:
            ratio_steps = policy_step - prefill_steps * policy_steps_per_iter
            per_rank_gradient_steps = ratio(ratio_steps / world_size)
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    data = sampler.sample(per_rank_gradient_steps)
                    key, train_key = jax.random.split(key)
                    act_params, host_metrics = trainer.train(
                        data,
                        cumulative_per_rank_gradient_steps,
                        train_key,
                        want_full_state=pending_ckpt,
                        want_metrics=bool(aggregator and not aggregator.disabled),
                    )
                    cumulative_per_rank_gradient_steps += per_rank_gradient_steps
                    train_step += world_size * per_rank_gradient_steps
                    trained_this_iter = True
                    telemetry.observe_train(
                        per_rank_gradient_steps,
                        host_metrics if host_metrics is not None else getattr(trainer, "last_metrics", None),
                    )
                    # the Learn/ keys ride the metrics dict; device refs are
                    # fine — telemetry only fetches them at window cadence
                    telemetry.observe_learn(
                        host_metrics if host_metrics is not None else getattr(trainer, "last_metrics", None)
                    )
                    if telemetry.wants_program("train_step") and getattr(trainer, "params", None) is not None:
                        # the compiled unit is the single fused gradient step the
                        # host G-loop drives; its batch aval is one [T, B] slice of
                        # the staged [G, T, B] block (metadata only, no device op;
                        # sharding preserved so the lowering matches the live program)
                        batch_avals = unit_avals(data)
                        telemetry.register_program(
                            "train_step",
                            trainer.train_phase.train_step,
                            (
                                trainer.params,
                                trainer.opt_state,
                                trainer.moments_state,
                                batch_avals,
                                jnp.asarray(cumulative_per_rank_gradient_steps),
                                jnp.asarray(train_key),
                            ),
                            units=1,
                        )
                    if host_metrics is not None and aggregator and not aggregator.disabled:
                        for mk, mv in host_metrics.items():
                            aggregator.update(mk, float(mv))

        # log
        telemetry.step(policy_step)
        resilience.step(policy_step)
        if cfg.metric.log_level > 0 and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run
        ):
            with timer("Time/logging_time"):
                metrics_dict = aggregator.compute() if aggregator else {}
                if logger is not None:
                    logger.log_metrics(metrics_dict, policy_step)
                    if policy_step > 0:
                        logger.log_metrics(
                            {
                                "Params/replay_ratio": cumulative_per_rank_gradient_steps
                                * world_size
                                / max(policy_step, 1)
                            },
                            policy_step,
                        )
                    timers = timer.to_dict(reset=False)
                    if timers.get("Time/train_time", 0) > 0:
                        logger.log_metrics(
                            {"Time/sps_train": (train_step - last_train) / max(timers["Time/train_time"], 1e-9)},
                            policy_step,
                        )
                    if timers.get("Time/env_interaction_time", 0) > 0:
                        logger.log_metrics(
                            {
                                "Time/sps_env_interaction": (
                                    (policy_step - last_log) / world_size * cfg.env.action_repeat
                                )
                                / max(timers["Time/env_interaction_time"], 1e-9)
                            },
                            policy_step,
                        )
                timer.to_dict(reset=True)
                if aggregator:
                    aggregator.reset()
            last_log = policy_step
            last_train = train_step

        # checkpoint (a deferring trainer only has full state at train rounds; its
        # last pending checkpoint, if any, is flushed by close() below; a trainer
        # with external_checkpoints — the service actor, whose LEARNER owns the
        # full state — never checkpoints from this loop at all)
        if (
            pending_ckpt
            and not getattr(trainer, "external_checkpoints", False)
            and (not trainer.defers_checkpoints or trained_this_iter)
        ):
            last_checkpoint = policy_step
            pending_ckpt = False
            ckpt_agent, ckpt_opt, ckpt_moments = trainer.checkpoint_state()
            ckpt_state = {
                "agent": ckpt_agent,
                "opt_state": ckpt_opt,
                "moments": ckpt_moments,
                "ratio": ratio.state_dict(),
                "iter_num": iter_num * world_size,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            # quiesce the prefetch worker so the pickled buffer (incl. its RNG
            # state) is not a torn mid-sample snapshot
            with sampler.lock, timer("Time/checkpoint_time"):
                fabric.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)
        if preempted:
            # still-pending emergency checkpoint (a deferring trainer without a
            # train round this iteration) is flushed by the close() path below;
            # breaking — rather than raising — runs the normal teardown, which
            # forwards the shutdown to channel trainer ranks
            break

    bench.finish(policy_step, trainer.sync_tree())

    sampler.close()
    final_state = trainer.close()
    if pending_ckpt and final_state is not None:
        # deferred last checkpoint: the learner's final full state rode the
        # shutdown handshake
        ckpt_agent, ckpt_opt, ckpt_moments = final_state
        ckpt_state = {
            "agent": ckpt_agent,
            "opt_state": ckpt_opt,
            "moments": ckpt_moments,
            "ratio": ratio.state_dict(),
            # iter_num (not total_iters): a preempt-break flushes here BEFORE the
            # run finished, and a resumed run must not think it completed
            "iter_num": iter_num * world_size,
            "batch_size": cfg.algo.per_rank_batch_size * world_size,
            "last_log": last_log,
            "last_checkpoint": policy_step,
        }
        ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
        # quiesce the prefetch worker so the pickled buffer (incl. its RNG
        # state) is not a torn mid-sample snapshot
        with sampler.lock, timer("Time/checkpoint_time"):
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
        resilience.observe_checkpoint(ckpt_path, policy_step, preempted=preempted)

    envs.close()
    # an in-flight async (orbax) checkpoint write must land before teardown
    wait_for_checkpoint()
    if not resilience.finalize(policy_step) and fabric.is_global_zero and cfg.algo.run_test:
        with timer("Time/test_time"):
            test_fn(player, act_params, fabric, cfg, log_dir, greedy=False)
    # closed AFTER the final test so the summary phases include eval time; an
    # exception path that skips this is flushed by cli.run_algorithm with
    # clean_exit=False
    telemetry.close(policy_step)
    if logger is not None:
        logger.finalize()


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    return run_dreamer(fabric, cfg)
