"""Dreamer-family serving extractors — the RSSM case of the O(1) session-state
argument (PAPERS.md arxiv 2603.09555 applied to world-model policies, see
howto/serving.md).

The per-session carry is exactly the player's per-env state: previous action,
recurrent state ``h``, stochastic state ``z``, plus the session PRNG key —
a few KB per slot regardless of episode length, device-resident, updated in
place by the donated slot-table step program. ``step_slot`` mirrors
``PlayerDV3._step`` per slot (encoder → recurrent → representation → actor
sample), so serving runs the same math as evaluation, vmapped over sessions.

``dreamer_v1``/``dreamer_v2`` reuse the same shape through
:func:`dreamer_serve_policy` with their own initial carries and actor samplers
(their ``serve.py`` modules parameterize it).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.serve.policy import ServePolicy, space_obs_spec
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.registry import register_serve_policy


def dreamer_serve_policy(
    fabric,
    cfg: Dict[str, Any],
    state: Dict[str, Any],
    *,
    build_agent: Callable,
    actor_sample: Callable,
    init_carry: Callable[[Any, Any], Tuple[jax.Array, jax.Array]],
    family: str,
) -> ServePolicy:
    """Shared Dreamer-family serving policy: ``init_carry(agent, wm_params)``
    returns the unbatched ``(h0, z0)`` pair for one fresh session."""
    env = make_env(cfg, cfg.seed, 0, None, "serve-probe")()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    action_shape = tuple(int(s) for s in action_space.shape)
    env.close()

    agent, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        jax.random.PRNGKey(cfg.seed),
        state["agent"] if state else None,
    )

    cnn_keys = tuple(cfg.algo.cnn_keys.encoder)
    mlp_keys = tuple(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    greedy = bool((cfg.get("serve") or {}).get("greedy", True))
    act_dim_total = int(np.sum(actions_dim))
    splits = np.cumsum(actions_dim)[:-1].tolist()

    def init_slot(params, key):
        h0, z0 = init_carry(agent, params["world_model"])
        return {
            "action": jnp.zeros((act_dim_total,), jnp.float32),
            "h": h0,
            "z": z0,
            "key": key,
        }

    def step_slot(params, carry, obs):
        key, k_repr, k_act = jax.random.split(carry["key"], 3)
        wm = params["world_model"]
        norm: Dict[str, jax.Array] = {}
        for k in obs_keys:
            v = obs[k].astype(jnp.float32)
            if k in cnn_keys:
                # frame-stack folds into channels; pixels -> [-0.5, 0.5]
                # (the dreamer prepare_obs path, per slot)
                norm[k] = v.reshape(-1, *v.shape[-2:]) / 255.0 - 0.5
            else:
                norm[k] = v.reshape(-1)
        embedded = agent.encoder.apply({"params": wm["encoder"]}, norm)
        h = agent._recurrent(wm, carry["z"], carry["action"], carry["h"])
        _, z = agent._representation(wm, h, embedded, k_repr)
        latent = jnp.concatenate([z, h], axis=-1)
        pre = agent.actor.apply({"params": params["actor"]}, latent)
        actions = actor_sample(agent, pre, k_act, greedy=greedy)
        if is_continuous:
            env_action = actions.reshape(action_shape).astype(jnp.float32)
        else:
            blocks = jnp.split(actions, splits, axis=-1)
            env_action = jnp.stack([b.argmax(axis=-1) for b in blocks], axis=-1).reshape(
                action_shape
            ).astype(jnp.int32)
        return env_action, {
            "action": actions.reshape(act_dim_total).astype(jnp.float32),
            "h": h,
            "z": z,
            "key": key,
        }

    return ServePolicy(
        algo=str(cfg.algo.name),
        params=params,
        init_slot=init_slot,
        step_slot=step_slot,
        obs_spec=space_obs_spec(observation_space, obs_keys),
        action_shape=action_shape,
        action_dtype=np.float32 if is_continuous else np.int32,
        meta={"family": family, "greedy": greedy, "recurrent": True},
    )


@register_serve_policy(algorithms=["dreamer_v3", "dreamer_v3_decoupled"])
def get_serve_policy(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> ServePolicy:
    from sheeprl_tpu.algos.dreamer_v3.agent import actor_sample, build_agent

    def init_carry(agent, wm_params):
        # learnable tanh(w) initial recurrent state + transition-mode posterior
        # (the same initial state PlayerDV3 resets to)
        return agent.initial_state(wm_params, ())

    return dreamer_serve_policy(
        fabric,
        cfg,
        state,
        build_agent=build_agent,
        actor_sample=actor_sample,
        init_carry=init_carry,
        family="dreamer_v3",
    )
