"""Fused-program registry + AOT contract sweep (the ``lint --aot`` gate).

Every donated ``jax.jit`` program in the tree (the fused train phases, the
serving slot-table step/attach, the Anakin fused rollout+train) registers an
**AOT builder** via :func:`register_fused_program`: a zero-argument callable
that constructs the jitted program on tiny shapes (composing a tiny config and
building the real agent — the same factories the training loops use) and
returns ``(jitted_fn, example_args)``. The sweep then, per program and WITHOUT
executing anything:

1. ``jit(...).trace(abstract_args).lower(lowering_platforms=(...))`` — the full
   jaxpr→StableHLO pipeline for BOTH the cpu and tpu platforms, off-chip (the
   ``test_tpu_lowering.py`` trick generalized: a branch that only ever lowered
   on CPU cannot hide a TPU trace error until the first paid chip window);
2. asserts the declared :class:`ProgramContract` on the lowered MLIR: donation
   survives (``jax.buffer_donor``/``tf.aliasing_output``), no host-transfer
   markers (``callback``/``infeed``/``outfeed``), no custom calls beyond the
   declared allowlist, expected custom calls present (the Pallas GRU's Mosaic
   ``tpu_custom_call``);
3. optionally backend-compiles on the host CPU mesh and asserts the OPTIMIZED
   HLO too: ``input_output_alias`` (XLA actually honored the donation) and the
   expected collective families (the dp psum of a data-parallel program).

This generalizes the three hand-written AOT tests (anakin, serve slots,
test_tpu_lowering) into one registry pass: those tests now parametrize over
:data:`FUSED_PROGRAMS` (``tests/test_analysis/test_aot_contracts.py``), and
``python sheeprl.py lint --aot`` runs the identical sweep operationally.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

Finding = Dict[str, Any]

__all__ = [
    "ProgramContract",
    "ProgramSpec",
    "FUSED_PROGRAMS",
    "register_fused_program",
    "check_program_contract",
    "aot_sweep",
]

# host-transfer markers that must never appear in a fused program's lowering
HOST_TRANSFER_MARKERS = ("callback", "infeed", "outfeed")

COLLECTIVE_FAMILIES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_CUSTOM_CALL_MLIR_RE = re.compile(r"custom_call\s+@([\w$.]+)")
_CUSTOM_CALL_TARGET_RE = re.compile(r'custom_call_target\s*=\s*"([^"]+)"')


@dataclass(frozen=True)
class ProgramContract:
    """What the lowered/compiled program must look like.

    ``donated``: donation aliasing must survive lowering (and, with
    ``compile_on_cpu``, the XLA optimization pipeline). ``min_donated`` guards
    against donation quietly narrowing to a subset of the state leaves.
    ``allow_custom_calls`` is the closed allowlist of custom-call targets
    (anything else is an unexpected host/runtime dependency);
    ``expect_custom_calls`` must each appear (e.g. the Mosaic kernel).
    ``expect_collectives`` are checked in the optimized HLO — declaring one
    implies ``compile_on_cpu``."""

    donated: bool = True
    min_donated: int = 1
    forbidden: Tuple[str, ...] = HOST_TRANSFER_MARKERS
    allow_custom_calls: Tuple[str, ...] = ()
    expect_custom_calls: Tuple[str, ...] = ()
    expect_collectives: Tuple[str, ...] = ()
    platforms: Tuple[str, ...] = ("cpu", "tpu")
    compile_on_cpu: bool = False


@dataclass
class ProgramSpec:
    name: str
    builder: Callable[[], Tuple[Any, Sequence[Any]]]
    contract: ProgramContract
    devices: int = 1
    origin: str = ""  # repo-relative file of the registration site
    doc: str = ""
    tags: Tuple[str, ...] = field(default_factory=tuple)


# name -> spec; populated at import time by the registering modules
# (``import sheeprl_tpu`` pulls in every algo module; serve/ops registrations
# ride the imports in ensure_registry()).
FUSED_PROGRAMS: Dict[str, ProgramSpec] = {}


def register_fused_program(
    name: str,
    *,
    donated: bool = True,
    min_donated: int = 1,
    allow_custom_calls: Sequence[str] = (),
    expect_custom_calls: Sequence[str] = (),
    expect_collectives: Sequence[str] = (),
    platforms: Sequence[str] = ("cpu", "tpu"),
    compile_on_cpu: bool = False,
    devices: int = 1,
    doc: str = "",
    tags: Sequence[str] = (),
) -> Callable:
    """Decorator: register ``builder() -> (jitted_fn, example_args)`` under
    ``name`` with its declared contract. The builder must be cheap enough for a
    tier-1 test (tiny shapes) and must construct the program through the SAME
    factory the training loop uses — the sweep's value is that it lowers
    exactly what production runs."""

    contract = ProgramContract(
        donated=donated,
        min_donated=min_donated,
        allow_custom_calls=tuple(allow_custom_calls),
        expect_custom_calls=tuple(expect_custom_calls),
        expect_collectives=tuple(expect_collectives),
        platforms=tuple(platforms),
        compile_on_cpu=bool(compile_on_cpu) or bool(expect_collectives),
    )

    def wrap(builder: Callable) -> Callable:
        if name in FUSED_PROGRAMS:
            raise ValueError(f"fused program {name!r} registered twice")
        module = getattr(builder, "__module__", "") or ""
        origin = module.replace(".", "/") + ".py" if module else ""
        FUSED_PROGRAMS[name] = ProgramSpec(
            name=name,
            builder=builder,
            contract=contract,
            devices=int(devices),
            origin=origin,
            doc=doc or (builder.__doc__ or "").strip().split("\n")[0],
            tags=tuple(tags),
        )
        return builder

    return wrap


def ensure_registry() -> Dict[str, ProgramSpec]:
    """Import every registering module (idempotent) and return the registry."""
    import importlib

    importlib.import_module("sheeprl_tpu")  # all algo modules
    for extra in ("sheeprl_tpu.serve.slots", "sheeprl_tpu.ops.aot"):
        importlib.import_module(extra)
    return FUSED_PROGRAMS


def _custom_call_targets(text: str) -> List[str]:
    targets = _CUSTOM_CALL_MLIR_RE.findall(text) + _CUSTOM_CALL_TARGET_RE.findall(text)
    return sorted(set(targets))


def _finding(spec: ProgramSpec, summary: str, suggestion: str, severity: str = "critical") -> Finding:
    return {
        "rule": "aot-contract",
        "severity": severity,
        "file": spec.origin or "sheeprl_tpu/analysis/programs.py",
        "line": 0,
        "summary": f"[{spec.name}] {summary}",
        "suggestion": suggestion,
    }


def check_program_contract(spec: ProgramSpec) -> List[Finding]:
    """Build, lower and (optionally) compile one registered program; return the
    contract violations as findings (empty list = contract holds).

    The process-wide partitioned-mesh gate is restored to its PRIOR value after
    each program: a mesh-building spec (anakin's 8-device fabric) flips it
    sticky, and a later single-device spec lowered under it would take the
    native paths instead of the fast paths production single-device runs lower
    — masking exactly the regressions the sweep exists to catch."""
    from sheeprl_tpu import ops

    prior_partitioned = ops.partitioned_mesh_active()
    try:
        return _check_program_contract(spec)
    finally:
        ops.set_partitioned_mesh(prior_partitioned)


def _check_program_contract(spec: ProgramSpec) -> List[Finding]:
    import jax

    from sheeprl_tpu.utils.mfu import abstractify

    contract = spec.contract
    findings: List[Finding] = []

    if spec.devices > 1 and len(jax.local_devices(backend="cpu")) < spec.devices:
        return [
            _finding(
                spec,
                f"skipped: needs a {spec.devices}-device host mesh "
                f"(set XLA_FLAGS=--xla_force_host_platform_device_count={spec.devices})",
                "run under the tier-1 harness or `python sheeprl.py lint --aot` "
                "(which pins the virtual host mesh before jax initializes)",
                severity="info",
            )
        ]

    try:
        fn, args = spec.builder()
    except Exception as exc:  # noqa: BLE001 - a failing builder IS the finding
        return [
            _finding(
                spec,
                f"AOT builder raised: {exc!r:.300}",
                "the builder must construct the program the loop runs; fix it or "
                "unregister the program",
            )
        ]

    abstract_args = abstractify(tuple(args))
    try:
        lowered = fn.trace(*abstract_args).lower(lowering_platforms=contract.platforms)
        mlir = lowered.as_text()
    except Exception as exc:  # noqa: BLE001
        return [
            _finding(
                spec,
                f"failed to lower for platforms {contract.platforms}: {exc!r:.300}",
                "this is exactly the class of error that otherwise surfaces on the "
                "first paid chip window — fix the lowering-sensitive branch",
            )
        ]

    lower_text = mlir.lower()
    if contract.donated:
        donors = mlir.count("jax.buffer_donor") + mlir.count("tf.aliasing_output")
        if donors < contract.min_donated:
            findings.append(
                _finding(
                    spec,
                    f"donation was dropped in lowering ({donors} donor annotation(s), "
                    f"expected >= {contract.min_donated})",
                    "check for host views (np.asarray) of donated inputs and for "
                    "out_shardings/jit wrappers that drop donate_argnums",
                )
            )
    for marker in contract.forbidden:
        if marker in lower_text:
            findings.append(
                _finding(
                    spec,
                    f"host-transfer marker {marker!r} in the lowered program",
                    "a fused program must not round-trip through the host in steady "
                    "state; hunt the callback/outfeed and move it out of the jit",
                )
            )
    allowed = set(contract.allow_custom_calls) | {"Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape"}
    unexpected = [t for t in _custom_call_targets(mlir) if t not in allowed]
    if unexpected:
        findings.append(
            _finding(
                spec,
                f"unexpected custom call(s) in lowering: {unexpected}",
                "declare deliberate kernels via allow_custom_calls=...; anything "
                "else is an undeclared runtime dependency",
            )
        )
    for expected in contract.expect_custom_calls:
        if expected not in mlir:
            findings.append(
                _finding(
                    spec,
                    f"expected custom call {expected!r} absent from the lowering",
                    "the declared kernel did not survive lowering (dispatch gate "
                    "changed? precision inherited?)",
                )
            )

    if contract.compile_on_cpu:
        try:
            compiled = fn.lower(*abstract_args).compile()
            hlo = compiled.as_text()
        except Exception as exc:  # noqa: BLE001
            findings.append(
                _finding(
                    spec,
                    f"failed to backend-compile on the host mesh: {exc!r:.300}",
                    "the CPU-mesh compile is the off-chip stand-in for the real "
                    "backend compile; fix before burning chip time",
                )
            )
            return findings
        hlo_lower = hlo.lower()
        if contract.donated and "input_output_alias" not in hlo:
            findings.append(
                _finding(
                    spec,
                    "XLA dropped the input/output aliasing in the optimized HLO",
                    "donation survived lowering but not compilation — look for "
                    "layout-change copies or output resharding on the donated leaves",
                )
            )
        for marker in contract.forbidden:
            if marker in hlo_lower:
                findings.append(
                    _finding(
                        spec,
                        f"host-transfer marker {marker!r} in the optimized HLO",
                        "the compiled steady-state program must keep the host out of "
                        "the loop",
                    )
                )
        for family in contract.expect_collectives:
            if family not in hlo_lower:
                findings.append(
                    _finding(
                        spec,
                        f"expected collective family {family!r} absent from the "
                        "optimized HLO",
                        "the mesh program no longer reduces across the declared axis "
                        "— sharding rules or mesh shape drifted",
                    )
                )
    return findings


# ---- shared tiny-construction helpers for the AOT builders --------------------
# The builders must construct REAL programs through the loops' own factories,
# but on shapes small enough that lowering the whole registry stays a tier-1
# test. These helpers hold the construction the dreamer-family builders share
# (the __graft_entry__ dryrun recipe); everything imports lazily so the module
# stays jax-free until a sweep actually runs.

DREAMER_TINY_OVERRIDES = (
    "env=dummy",
    "fabric.accelerator=cpu",
    "env.num_envs=2",
    "env.capture_video=False",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.cnn_keys.decoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "algo.mlp_keys.decoder=[state]",
    "algo.per_rank_batch_size=2",
    "algo.per_rank_sequence_length=4",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.world_model.stochastic_size=4",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.representation_model.hidden_size=8",
    "buffer.memmap=False",
    "metric.log_level=0",
    # the AOT gate must lower the GROWN programs: the Learn/* stats block is
    # compiled in only under the telemetry learning plane (utils/learn_stats.py)
    "metric.telemetry.enabled=true",
)


def tiny_dreamer_cfg(exp: str, extra: Sequence[str] = ()):
    """Compose ``exp`` at the tiny shapes every dreamer-family AOT builder uses."""
    from sheeprl_tpu.config import compose

    return compose([f"exp={exp}", *DREAMER_TINY_OVERRIDES, *extra])


def tiny_fabric():
    """Single-device CPU fabric, set up (pins the platform before any device op)."""
    from sheeprl_tpu.parallel.fabric import Fabric

    fabric = Fabric(devices=1, accelerator="cpu")
    fabric._setup()
    return fabric


def tiny_obs_space(screen: int = 64, state_dim: int = 10):
    import gymnasium as gym
    import numpy as np

    return gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (3, screen, screen), np.uint8),
            "state": gym.spaces.Box(-np.inf, np.inf, (state_dim,), np.float32),
        }
    )


def tiny_dreamer_batch(cfg, n_actions: int = 4, screen: int = 64, state_dim: int = 10):
    """One ``[T, B, ...]`` replay slice matching :func:`tiny_dreamer_cfg`'s
    shapes — the single-gradient-step unit the fused ``train_step`` consumes."""
    import numpy as np

    T = int(cfg.algo.per_rank_sequence_length)
    B = int(cfg.algo.per_rank_batch_size)
    rng = np.random.default_rng(0)
    return {
        "rgb": rng.integers(0, 255, (T, B, 3, screen, screen)).astype(np.uint8),
        "state": rng.normal(size=(T, B, state_dim)).astype(np.float32),
        "actions": np.eye(n_actions, dtype=np.float32)[rng.integers(0, n_actions, (T, B))],
        "rewards": rng.normal(size=(T, B, 1)).astype(np.float32),
        "terminated": np.zeros((T, B, 1), np.float32),
        "truncated": np.zeros((T, B, 1), np.float32),
        "is_first": np.zeros((T, B, 1), np.float32),
    }


def aot_sweep(names: Optional[Sequence[str]] = None) -> Tuple[List[Finding], int]:
    """Run the contract check over every registered program (or ``names``).
    Returns ``(findings, programs_checked)``. Each program check restores the
    process-wide partitioned-mesh gate to its prior value (see
    :func:`check_program_contract`), so the sweep never changes which kernels
    the hosting process — or the next program in the sweep — lowers."""
    registry = ensure_registry()
    specs = [registry[n] for n in names] if names else list(registry.values())
    findings: List[Finding] = []
    for spec in specs:
        findings.extend(check_program_contract(spec))
    return findings, len(specs)
