"""Waiver file for the lint gate (``sheeprl_tpu/analysis/waivers.toml``).

The gate's contract is ZERO unwaived findings: a finding that is a deliberate,
understood exception gets a checked-in waiver **with a reason** instead of a
silent rule carve-out — so every exception is visible in review and re-audited
whenever the file churns. Format (a small TOML subset — this image's Python is
3.10, no ``tomllib``, and no third-party toml parser is installed):

.. code-block:: toml

    [[waiver]]
    rule = "host-sync-in-jit"           # required: the rule name
    file = "sheeprl_tpu/algos/x.py"     # required: finding's repo-relative file
    line = 123                          # optional: pin to a line (omit = whole file)
    reason = "why this is deliberate"   # required, non-empty

The parser accepts exactly what the file needs: ``[[waiver]]`` array-of-table
headers, ``key = "string" | integer | true/false`` pairs, and ``#`` comments.
Anything else is a hard error — a malformed waiver must never silently waive
nothing (or everything).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["WaiverError", "load_waivers", "match_waiver", "apply_waivers"]

DEFAULT_WAIVERS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "waivers.toml")

_KV_RE = re.compile(r"^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*(.+)$")


class WaiverError(ValueError):
    pass


def _parse_value(raw: str, where: str) -> Any:
    raw = raw.strip()
    if raw.startswith('"'):
        if not raw.endswith('"') or len(raw) < 2:
            raise WaiverError(f"{where}: unterminated string {raw!r}")
        body = raw[1:-1]
        if '"' in body:
            raise WaiverError(f"{where}: embedded quotes are not supported: {raw!r}")
        return body
    if raw in ("true", "false"):
        return raw == "true"
    if re.fullmatch(r"[+-]?\d+", raw):
        return int(raw)
    raise WaiverError(f"{where}: unsupported value {raw!r} (use a quoted string or an integer)")


def parse_waivers_toml(text: str, path: str = "<waivers>") -> List[Dict[str, Any]]:
    waivers: List[Dict[str, Any]] = []
    current: Optional[Dict[str, Any]] = None
    for lineno, raw_line in enumerate(text.splitlines(), start=1):
        line = raw_line.strip()
        if "#" in line:
            # comments: full-line, or trailing after a value (never inside the
            # one-double-quote-delimited strings this subset allows... unless the
            # string itself contains '#', which _parse_value would then reject)
            head = line.split("#", 1)[0].rstrip()
            if head or not line.startswith("#"):
                line = head
            else:
                continue
        if not line:
            continue
        where = f"{path}:{lineno}"
        if line == "[[waiver]]":
            current = {}
            waivers.append(current)
            continue
        if line.startswith("["):
            raise WaiverError(f"{where}: only [[waiver]] tables are supported, got {line!r}")
        m = _KV_RE.match(line)
        if m is None:
            raise WaiverError(f"{where}: cannot parse line {raw_line!r}")
        if current is None:
            raise WaiverError(f"{where}: key/value pair outside a [[waiver]] table")
        current[m.group(1)] = _parse_value(m.group(2), where)
    for i, w in enumerate(waivers):
        for required in ("rule", "file", "reason"):
            if not isinstance(w.get(required), str) or not w[required].strip():
                raise WaiverError(
                    f"{path}: waiver #{i + 1} needs a non-empty string {required!r} "
                    "(every waiver must name its rule, its file, and carry a reason)"
                )
        if "line" in w and not isinstance(w["line"], int):
            raise WaiverError(f"{path}: waiver #{i + 1} 'line' must be an integer")
        unknown = set(w) - {"rule", "file", "line", "reason"}
        if unknown:
            raise WaiverError(f"{path}: waiver #{i + 1} has unknown keys {sorted(unknown)}")
    return waivers


def load_waivers(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Parse the waiver file (the checked-in default when ``path`` is None).
    A missing file is an empty waiver list, not an error."""
    path = path or DEFAULT_WAIVERS_PATH
    if not os.path.isfile(path):
        return []
    with open(path) as f:
        return parse_waivers_toml(f.read(), path=path)


def match_waiver(finding: Dict[str, Any], waivers: Sequence[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    for w in waivers:
        if w["rule"] != finding.get("rule") or w["file"] != finding.get("file"):
            continue
        if "line" in w and w["line"] != finding.get("line"):
            continue
        return w
    return None


def apply_waivers(
    findings: Sequence[Dict[str, Any]], waivers: Sequence[Dict[str, Any]]
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Split ``findings`` into (active, waived) and report unused waivers.

    Waived findings carry their waiver's reason under ``waived_reason``. Unused
    waivers (matching nothing) are returned so the gate can flag stale entries —
    a waiver that outlived its finding should be deleted, not accumulated."""
    active: List[Dict[str, Any]] = []
    waived: List[Dict[str, Any]] = []
    used: set = set()
    for finding in findings:
        w = match_waiver(finding, waivers)
        if w is None:
            active.append(dict(finding))
        else:
            used.add(id(w))
            waived.append({**finding, "waived_reason": w["reason"]})
    unused = [w for w in waivers if id(w) not in used]
    return active, waived, unused
