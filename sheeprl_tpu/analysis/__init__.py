"""JAX-aware static analysis + AOT program-contract gate (``sheeprl.py lint``).

Every hazard class this framework has hit shipped first and was caught later by
a one-off fix: the ``platform_dependent`` TPU branch that lowered on CPU (PR 1),
``jax.devices()`` handing a non-rank-0 actor another process's device (PR 10),
the Pallas GRU inheriting an unsupported Mosaic dot precision (PR 10), donation
silently disabled by ``np.asarray`` host views (PR 1), and telemetry events
emitted outside the schema registry (PR 11). This package turns each of those
into a standing, pre-chip check:

- :mod:`~sheeprl_tpu.analysis.engine` walks the package's AST once and runs the
  rule catalog (:mod:`~sheeprl_tpu.analysis.rules`), yielding findings shaped
  like ``obs/diagnose.py``'s: {rule, severity, file, line, summary, suggestion};
- :mod:`~sheeprl_tpu.analysis.programs` is the fused-program registry: the
  donated ``jax.jit`` programs of algos/serve register an AOT builder via
  :func:`register_fused_program`, and :func:`aot_sweep` lowers each for
  ("cpu", "tpu") off-chip and asserts its declared contract (donation survives,
  no host callbacks, expected collectives/custom calls present);
- :mod:`~sheeprl_tpu.analysis.waivers` reads the checked-in
  ``analysis/waivers.toml`` (every entry requires a reason) so the gate starts
  at zero findings and stays there.

See ``howto/static_analysis.md`` for the rule catalog and waiver format.
"""

from sheeprl_tpu.analysis.engine import Finding, lint_main, run_lint
from sheeprl_tpu.analysis.programs import (
    FUSED_PROGRAMS,
    ProgramContract,
    aot_sweep,
    check_program_contract,
    register_fused_program,
)
from sheeprl_tpu.analysis.waivers import load_waivers

__all__ = [
    "Finding",
    "run_lint",
    "lint_main",
    "load_waivers",
    "register_fused_program",
    "FUSED_PROGRAMS",
    "ProgramContract",
    "aot_sweep",
    "check_program_contract",
]
