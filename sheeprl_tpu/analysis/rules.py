"""The lint rule catalog: each rule codifies one JAX/TPU hazard class this
repo has actually hit (see the module docstring of :mod:`sheeprl_tpu.analysis`
for the incident list). Rules are AST visitors over the package's parsed
sources — **no sheeprl_tpu module is imported** by any rule (the engine must
stay fast and never initialize jax), with one deliberate exception:
``cfg-key-resolves`` composes the repo's own YAML config tree through
``sheeprl_tpu.config`` (pure YAML, no jax).

Each rule yields findings shaped like ``obs/diagnose.py``'s:
``{rule, severity, file, line, summary, suggestion}``.

Adding a rule: subclass :class:`Rule`, set ``name``/``severity``, implement
``run(package)``, append it to :data:`ALL_RULES`, document it in
``howto/static_analysis.md``, and give it a positive + negative fixture test in
``tests/test_analysis/test_rules.py``.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

Finding = Dict[str, Any]

SEVERITIES = ("critical", "warning", "info")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.platform_dependent`` for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _set_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._lint_parent = node  # type: ignore[attr-defined]


def _enclosing_functions(node: ast.AST) -> List[ast.AST]:
    """Innermost-first chain of enclosing function defs (requires _set_parents)."""
    out: List[ast.AST] = []
    cur = getattr(node, "_lint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(cur)
        cur = getattr(cur, "_lint_parent", None)
    return out


def _local_defs(tree: ast.AST) -> Dict[str, List[ast.FunctionDef]]:
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _called_names(tree: ast.AST) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            names.add(node.func.id)
    return names


def _unwrap_partial(node: ast.AST) -> ast.AST:
    """``functools.partial(f, ...)`` / ``partial(f, ...)`` -> ``f``."""
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        if fn in ("partial", "functools.partial") and node.args:
            return node.args[0]
    return node


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _literal_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    try:
        value = ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return None
    if isinstance(value, int):
        return (value,)
    if isinstance(value, (tuple, list)) and all(isinstance(v, int) for v in value):
        return tuple(value)
    return None


class Rule:
    """Base rule. ``run(package)`` yields findings; ``package`` is the
    :class:`~sheeprl_tpu.analysis.engine.Package` of parsed sources."""

    name: str = ""
    severity: str = "warning"
    doc: str = ""

    def run(self, package) -> Iterator[Finding]:  # pragma: no cover - interface
        raise NotImplementedError

    def finding(
        self,
        module,
        node: Optional[ast.AST],
        summary: str,
        suggestion: str,
        severity: Optional[str] = None,
    ) -> Finding:
        return {
            "rule": self.name,
            "severity": severity or self.severity,
            "file": module.rel,
            "line": int(getattr(node, "lineno", 0) or 0),
            "summary": summary,
            "suggestion": suggestion,
        }


class JaxDevicesRule(Rule):
    """``jax.devices()`` outside ``parallel/fabric.py``.

    ``jax.devices()`` spans ALL processes of a multi-process run: on a
    multi-host pod, index 0 is rank 0's device — a non-rank-0 actor that grabs
    ``jax.devices()[0]`` is addressing ANOTHER process's chip (the PR 10
    serving-actor bug). ``parallel/fabric.py`` owns the only deliberate
    global-view call sites (mesh construction)."""

    name = "jax-devices-global-view"
    severity = "warning"
    doc = "jax.devices() outside parallel/fabric.py (use jax.local_devices())"

    ALLOWED_FILES = ("sheeprl_tpu/parallel/fabric.py",)

    def run(self, package) -> Iterator[Finding]:
        for module in package.modules:
            if module.rel in self.ALLOWED_FILES:
                continue
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call) and dotted_name(node.func) == "jax.devices":
                    yield self.finding(
                        module,
                        node,
                        "jax.devices() addresses the GLOBAL device list — on a "
                        "multi-process run index 0 may be another process's chip",
                        "use jax.local_devices() (or thread the device through "
                        "parallel/fabric.py, the one module allowed a global view)",
                    )


class PlatformDependentGateRule(Rule):
    """``lax.platform_dependent(tpu=...)`` branches must be built only under a
    ``jax.default_backend()`` gate.

    ``platform_dependent`` lowers EVERY branch for every requested platform —
    a Pallas TPU kernel in the ``tpu=`` branch refuses to lower for CPU, so an
    ungated dispatch traces fine on a TPU process and explodes on any CPU
    process (the PR 1 seed failure: every dreamer-family CPU test red)."""

    name = "platform-dependent-ungated"
    severity = "critical"
    doc = "platform_dependent TPU branch without a jax.default_backend() gate"

    def run(self, package) -> Iterator[Finding]:
        for module in package.modules:
            if "platform_dependent" not in module.source:
                continue
            _set_parents(module.tree)
            for node in ast.walk(module.tree):
                if not (isinstance(node, ast.Call) and (dotted_name(node.func) or "").endswith("platform_dependent")):
                    continue
                if not any(kw.arg == "tpu" for kw in node.keywords):
                    continue  # cpu=/default= fast-path gates lower everywhere
                scopes: Sequence[ast.AST] = _enclosing_functions(node) or [module.tree]
                gate_scope = scopes[-1]  # outermost function (or the module)
                gated = any(
                    isinstance(n, ast.Call)
                    and (dotted_name(n.func) or "").endswith("default_backend")
                    for n in ast.walk(gate_scope)
                )
                if not gated:
                    yield self.finding(
                        module,
                        node,
                        "platform_dependent(tpu=...) built without a "
                        "jax.default_backend() gate — the TPU branch lowers (and "
                        "fails) on every CPU process",
                        'guard the dispatch with `jax.default_backend() == "tpu"` '
                        "(see models.py LayerNormGRUCell for the pattern)",
                    )


class PallasDotPrecisionRule(Rule):
    """Pallas kernel ``dot``s must pin an explicit ``precision=``.

    Mosaic only lowers DEFAULT/HIGHEST dot precisions, and the repo's global
    default is "high" (bf16_3x): an unpinned kernel dot inherits it and the
    whole kernel fails to lower for TPU (the PR 10 GRU bug, caught by the AOT
    suite). The rule finds the kernel functions (first argument of each
    ``pallas_call``, ``functools.partial`` unwrapped) and flags dot-family
    calls without a ``precision=`` keyword, plus bare ``@`` matmuls (which
    cannot pin one at all)."""

    name = "pallas-dot-precision"
    severity = "critical"
    doc = "Pallas kernel dot/matmul without an explicit precision="

    _DOT_ATTRS = ("dot", "dot_general", "matmul", "einsum")

    def run(self, package) -> Iterator[Finding]:
        for module in package.modules:
            if "pallas_call" not in module.source:
                continue
            kernels: Set[str] = set()
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call) and (dotted_name(node.func) or "").endswith("pallas_call"):
                    if node.args:
                        target = _unwrap_partial(node.args[0])
                        name = dotted_name(target)
                        if name:
                            kernels.add(name.split(".")[-1])
            if not kernels:
                continue
            defs = _local_defs(module.tree)
            for kernel_name in sorted(kernels):
                for kernel in defs.get(kernel_name, []):
                    for node in ast.walk(kernel):
                        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                            yield self.finding(
                                module,
                                node,
                                f"bare `@` matmul in Pallas kernel {kernel_name!r} "
                                "cannot pin a dot precision",
                                "use jnp.dot(..., precision=jax.lax.Precision.DEFAULT) "
                                "so the kernel never inherits the global bf16_3x default "
                                "Mosaic refuses to lower",
                            )
                            continue
                        if not isinstance(node, ast.Call):
                            continue
                        fn = dotted_name(node.func) or ""
                        if fn.split(".")[-1] not in self._DOT_ATTRS:
                            continue
                        if not any(kw.arg == "precision" for kw in node.keywords):
                            yield self.finding(
                                module,
                                node,
                                f"{fn}(...) in Pallas kernel {kernel_name!r} has no "
                                "explicit precision= and inherits the global matmul "
                                "precision (bf16_3x), which Mosaic cannot lower",
                                "pin precision=jax.lax.Precision.DEFAULT (MXU-native) "
                                "or HIGHEST inside the kernel",
                            )


def _donated_programs(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """name -> donated argnums, for both spellings used in the repo:
    ``@partial(jax.jit, donate_argnums=...)`` on a def, and
    ``name = jax.jit(fn, donate_argnums=...)`` / ``self._x = jax.jit(...)``."""
    donated: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if not isinstance(deco, ast.Call):
                    continue
                fn = dotted_name(deco.func)
                is_partial_jit = fn in ("partial", "functools.partial") and deco.args and _is_jax_jit(deco.args[0])
                if not (is_partial_jit or _is_jax_jit(deco.func)):
                    continue
                for kw in deco.keywords:
                    if kw.arg == "donate_argnums":
                        nums = _literal_int_tuple(kw.value)
                        if nums:
                            donated[node.name] = nums
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if not _is_jax_jit(call.func):
                continue
            nums: Optional[Tuple[int, ...]] = None
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    nums = _literal_int_tuple(kw.value)
            if not nums:
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    donated[target.id] = nums
                elif isinstance(target, ast.Attribute):
                    donated[target.attr] = nums
    return donated


class AsarrayDonationRule(Rule):
    """``np.asarray`` feeding a donated argument.

    On the CPU backend ``np.asarray`` of a device array hands out a zero-copy
    HOST VIEW that pins the underlying buffer — XLA then silently refuses the
    donation and the train state is copied every step (the PR 1 regression the
    donation tests pin). The rule resolves each module's donated programs
    (``donate_argnums`` spellings) and flags call sites whose DONATED argument
    positions receive ``np.asarray``/``np.array`` results, directly or through
    a local variable."""

    name = "asarray-into-donated"
    severity = "warning"
    doc = "np.asarray host view passed at a donated argument position"

    _NP_CONV = ("np.asarray", "np.array", "numpy.asarray", "numpy.array")

    def _is_np_conversion(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and dotted_name(node.func) in self._NP_CONV

    def run(self, package) -> Iterator[Finding]:
        for module in package.modules:
            if "donate_argnums" not in module.source:
                continue
            donated = _donated_programs(module.tree)
            if not donated:
                continue
            _set_parents(module.tree)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                callee: Optional[str] = None
                if isinstance(node.func, ast.Name) and node.func.id in donated:
                    callee = node.func.id
                elif isinstance(node.func, ast.Attribute) and node.func.attr in donated:
                    callee = node.func.attr
                if callee is None:
                    continue
                # variables assigned from np conversions in the enclosing function
                host_views: Set[str] = set()
                scopes = _enclosing_functions(node)
                if scopes:
                    for n in ast.walk(scopes[0]):
                        if isinstance(n, ast.Assign) and self._is_np_conversion(n.value):
                            for target in n.targets:
                                if isinstance(target, ast.Name):
                                    host_views.add(target.id)
                for pos in donated[callee]:
                    if pos >= len(node.args):
                        continue
                    arg = node.args[pos]
                    bad = self._is_np_conversion(arg) or (
                        isinstance(arg, ast.Name) and arg.id in host_views
                    )
                    if bad:
                        yield self.finding(
                            module,
                            node,
                            f"donated argument {pos} of {callee!r} is an "
                            "np.asarray/np.array host view — the pinned buffer "
                            "silently disables donation",
                            "snapshot with jnp.array (a device copy) before feeding "
                            "a donated program; see tests/test_algos/test_donation.py",
                        )


class HostSyncInJitRule(Rule):
    """Host-sync calls inside functions reachable from a jitted program.

    ``.item()``, ``np.array``/``np.asarray``, ``time.time`` and ``print`` on a
    traced value either fail at trace time or (worse) silently bake a
    trace-time constant into the compiled program; inside a jitted fused loop
    they are always a bug. The rule collects each module's jit roots (both
    decorator spellings and ``jax.jit(fn)`` wrapping of a local def), walks the
    intra-module call graph, and flags host-sync calls in the reachable set."""

    name = "host-sync-in-jit"
    severity = "warning"
    doc = "host-sync call (.item()/np.array/time.time/print) reachable from a jitted program"

    _TIME_CALLS = ("time.time", "time.perf_counter", "time.monotonic")
    _NP_CONV = ("np.asarray", "np.array", "numpy.asarray", "numpy.array")

    def _jit_roots(self, module) -> List[ast.FunctionDef]:
        roots: List[ast.FunctionDef] = []
        defs = _local_defs(module.tree)
        for name_defs in defs.values():
            for node in name_defs:
                for deco in node.decorator_list:
                    if _is_jax_jit(deco):
                        roots.append(node)
                    elif isinstance(deco, ast.Call):
                        fn = dotted_name(deco.func)
                        if _is_jax_jit(deco.func):
                            roots.append(node)
                        elif fn in ("partial", "functools.partial") and deco.args and _is_jax_jit(deco.args[0]):
                            roots.append(node)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and _is_jax_jit(node.func) and node.args:
                target = _unwrap_partial(node.args[0])
                # only bare local names: `jax.jit(self._env.reset)` wraps ANOTHER
                # object's method, not the local def that happens to share the name
                if isinstance(target, ast.Name):
                    for d in defs.get(target.id, []):
                        roots.append(d)
        return roots

    def run(self, package) -> Iterator[Finding]:
        for module in package.modules:
            if "jit" not in module.source:
                continue
            roots = self._jit_roots(module)
            if not roots:
                continue
            defs = _local_defs(module.tree)
            reachable: List[ast.FunctionDef] = []
            seen: Set[int] = set()
            frontier = list(roots)
            while frontier:
                fn = frontier.pop()
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                reachable.append(fn)
                for called in _called_names(fn):
                    for d in defs.get(called, []):
                        if id(d) not in seen:
                            frontier.append(d)
            flagged: Set[int] = set()
            for fn in reachable:
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call) or id(node) in flagged:
                        continue
                    name = dotted_name(node.func) or ""
                    what = None
                    if isinstance(node.func, ast.Attribute) and node.func.attr == "item" and not node.args:
                        what = ".item() device sync"
                    elif name in self._TIME_CALLS:
                        what = f"{name}() wall-clock read (a trace-time constant inside jit)"
                    elif name == "print":
                        what = "print() host callback"
                    elif name in self._NP_CONV:
                        what = f"{name}() host transfer"
                    elif isinstance(node.func, ast.Attribute) and node.func.attr == "block_until_ready":
                        what = "block_until_ready() device sync"
                    if what is not None:
                        flagged.add(id(node))
                        yield self.finding(
                            module,
                            node,
                            f"{what} inside {fn.name!r}, which is reachable from a "
                            "jitted program",
                            "keep host syncs outside the jitted program (or use "
                            "jax.debug.print / jnp equivalents); waive with a reason "
                            "if this path provably runs at trace time only",
                        )


class TelemetryEventSchemaRule(Rule):
    """Every emitted telemetry event type must be registered in ``obs/schema.py``.

    The stream's consumers parse with defaults, so an unregistered event type
    would not crash anything — it would silently fall out of every detector
    (the PR 11 drift class). This is the same census the PR 11 grep test ran,
    as an AST rule: ``emit``/``emit_event``/``_emit`` call sites with a literal
    event name are checked against the schema's declared event tables."""

    name = "telemetry-event-unregistered"
    severity = "critical"
    doc = "emit site whose event name is absent from obs/schema.py"

    _EMITTERS = ("emit", "emit_event", "_emit")

    def __init__(self, registered_names: Optional[Set[str]] = None) -> None:
        self._registered_override = registered_names

    def registered_names(self, package) -> Optional[Set[str]]:
        if self._registered_override is not None:
            return set(self._registered_override)
        schema = package.module("sheeprl_tpu/obs/schema.py")
        if schema is None:
            return None
        names: Set[str] = set()
        for node in ast.walk(schema.tree):
            # both spellings: `_X = {...}` and the annotated `_X: Dict[...] = {...}`
            if isinstance(node, ast.Assign):
                targets = {t.id for t in node.targets if isinstance(t, ast.Name)}
                value = node.value
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                targets = {node.target.id}
                value = node.value
            else:
                continue
            if not targets & {"_STRICT_EVENTS", "_OPEN_EVENTS"}:
                continue
            if isinstance(value, ast.Dict):
                for key in value.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        names.add(key.value)
        return names or None

    def emitted_events(self, package) -> List[Tuple[Any, ast.Call, str]]:
        """All (module, call, event_name) literal emit sites in the package —
        shared with the schema census test so the two checkers cannot drift."""
        sites: List[Tuple[Any, ast.Call, str]] = []
        for module in package.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = (dotted_name(node.func) or "").split(".")[-1]
                if fn not in self._EMITTERS or not node.args:
                    continue
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    sites.append((module, node, first.value))
        return sites

    def run(self, package) -> Iterator[Finding]:
        registered = self.registered_names(package)
        if registered is None:
            return  # no schema in this tree (fixture packages) and no override
        for module, node, event in self.emitted_events(package):
            if module.rel == "sheeprl_tpu/obs/schema.py":
                continue
            if event not in registered:
                yield self.finding(
                    module,
                    node,
                    f"telemetry event {event!r} is emitted but not registered in "
                    "obs/schema.py — consumers would silently ignore it",
                    "declare the event's field table in obs/schema.py (and bump "
                    "SCHEMA_VERSION if the change is breaking)",
                )


class LoopHooksRule(Rule):
    """Every registered algorithm entrypoint must thread the telemetry and
    resilience hook sets.

    PR 2/3 threaded 4 telemetry hooks (build, observe_train, step, close) and
    4 resilience hooks (build, step, preempt poll, finalize) through all
    training loops, and the learning-health plane added ``observe_learn`` (the
    fused program's ``Learn/*`` stats threading) as a fifth telemetry hook; a
    NEW algo registered without them trains blind (no phases/MFU/diagnosis, no
    learning-health detectors) and cannot be preempted safely. The rule finds
    every ``@register_algorithm``-decorated def, follows its intra-package call
    graph (local defs + ``from sheeprl_tpu... import`` helpers, so delegation
    through ``run_dreamer``/``run_anakin`` counts), and requires each hook to
    appear somewhere in the reachable set. A loop where a hook is structurally
    N/A (e.g. a driver with no train rounds of its own) waives it per file in
    ``analysis/waivers.toml`` with a reason, like any other rule."""

    name = "loop-hooks-incomplete"
    severity = "critical"
    doc = "registered algo entrypoint missing telemetry/resilience hooks"

    TELEMETRY_HOOKS = (
        "build_telemetry",
        "observe_train",
        "observe_learn",
        "telemetry.step",
        "telemetry.close",
    )
    RESILIENCE_HOOKS = (
        "build_resilience",
        "resilience.step",
        "preempt_requested",
        "resilience.finalize",
    )
    _MAX_DEPTH = 6

    def _entrypoints(self, package) -> List[Tuple[Any, ast.FunctionDef]]:
        out = []
        for module in package.modules:
            if "register_algorithm" not in module.source:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for deco in node.decorator_list:
                    target = deco.func if isinstance(deco, ast.Call) else deco
                    if (dotted_name(target) or "").split(".")[-1] == "register_algorithm":
                        out.append((module, node))
        return out

    def _imports(self, module) -> Dict[str, Tuple[str, str]]:
        """local name -> (source module rel path, original name) for
        ``from sheeprl_tpu.x.y import z [as w]`` imports."""
        imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ImportFrom) or not node.module:
                continue
            rel = node.module.replace(".", "/") + ".py"
            for alias in node.names:
                imports[alias.asname or alias.name] = (rel, alias.name)
        return imports

    def _module_aliases(self, package, module) -> Dict[str, str]:
        """local alias -> module rel path, for module-object imports
        (``from sheeprl_tpu.algos.dreamer_v1 import dreamer_v1 as dv1``,
        ``import sheeprl_tpu.x.y as z``) — so delegation spelled as an
        attribute call (``dv1.main(...)``) is followed too."""
        aliases: Dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    rel = f"{node.module.replace('.', '/')}/{alias.name}.py"
                    if package.module(rel) is not None:
                        aliases[alias.asname or alias.name] = rel
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    rel = alias.name.replace(".", "/") + ".py"
                    if package.module(rel) is not None:
                        aliases[alias.asname or alias.name.split(".")[0]] = rel
        return aliases

    def _module_tables(self, package, mod):
        """Per-module (defs, imports, aliases), cached — the tables are pure
        functions of the parsed tree, and recomputing them per visited function
        made the traversal quadratic (~7 s on this tree; cached it is linear)."""
        cached = self._tables_cache.get(mod.rel)
        if cached is None:
            cached = (
                _local_defs(mod.tree),
                self._imports(mod),
                self._module_aliases(package, mod),
            )
            self._tables_cache[mod.rel] = cached
        return cached

    def _reachable(self, package, module, entry: ast.FunctionDef) -> List[ast.AST]:
        reachable: List[ast.AST] = []
        seen: Set[Tuple[str, str]] = set()
        frontier: List[Tuple[Any, ast.AST, int]] = [(module, entry, 0)]
        while frontier:
            mod, fn, depth = frontier.pop()
            key = (mod.rel, getattr(fn, "name", "<module>"))
            if key in seen:
                continue
            seen.add(key)
            reachable.append(fn)
            if depth >= self._MAX_DEPTH:
                continue
            defs, imports, aliases = self._module_tables(package, mod)
            for called in _called_names(fn):
                for d in defs.get(called, []):
                    frontier.append((mod, d, depth + 1))
                if called in imports:
                    rel, original = imports[called]
                    target_mod = package.module(rel)
                    if target_mod is not None:
                        target_defs = self._module_tables(package, target_mod)[0]
                        for d in target_defs.get(original, []):
                            frontier.append((target_mod, d, depth + 1))
            # attribute calls through module aliases: dv1.main(...)
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in aliases
                ):
                    target_mod = package.module(aliases[node.func.value.id])
                    if target_mod is not None:
                        target_defs = self._module_tables(package, target_mod)[0]
                        for d in target_defs.get(node.func.attr, []):
                            frontier.append((target_mod, d, depth + 1))
        return reachable

    def _hooks_present(self, reachable: Sequence[ast.AST]) -> Set[str]:
        present: Set[str] = set()
        for fn in reachable:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Name):
                    if node.func.id in ("build_telemetry", "build_resilience"):
                        present.add(node.func.id)
                elif isinstance(node.func, ast.Attribute):
                    attr = node.func.attr
                    owner = dotted_name(node.func.value) or ""
                    owner_leaf = owner.split(".")[-1]
                    if attr in ("observe_train", "observe_learn", "preempt_requested"):
                        present.add(attr)
                    if attr in ("step", "close", "finalize") and (
                        "telemetry" in owner_leaf or "resilience" in owner_leaf
                    ):
                        kind = "telemetry" if "telemetry" in owner_leaf else "resilience"
                        present.add(f"{kind}.{attr}")
        return present

    def run(self, package) -> Iterator[Finding]:
        self._tables_cache: Dict[str, Tuple[Any, Any, Any]] = {}
        for module, entry in self._entrypoints(package):
            reachable = self._reachable(package, module, entry)
            present = self._hooks_present(reachable)
            missing_telemetry = [h for h in self.TELEMETRY_HOOKS if h not in present]
            missing_resilience = [h for h in self.RESILIENCE_HOOKS if h not in present]
            missing = missing_telemetry + missing_resilience
            if missing:
                yield self.finding(
                    module,
                    entry,
                    f"registered entrypoint {entry.name!r} does not thread "
                    f"{len(missing)} required loop hook(s): {', '.join(missing)}",
                    "thread the telemetry hooks (build_telemetry / observe_train / "
                    "observe_learn / telemetry.step / telemetry.close) and resilience "
                    "hooks (build_resilience / resilience.step / preempt_requested / "
                    "resilience.finalize) — see any existing loop, e.g. sac.py; waive "
                    "per file in analysis/waivers.toml where a hook is structurally N/A",
                )


class CfgKeyResolvesRule(Rule):
    """``cfg.<group>.<key>`` attribute chains must resolve against the composed
    YAML config tree.

    The config layer is plain ``dotdict``s: a typo'd or removed key raises
    ``AttributeError`` only when that exact line runs — on a 25-minute TPU
    workload, possibly an hour in. The rule composes every experiment through
    the repo's own composer, unions the resulting trees (a key present in ANY
    exp is valid — algo groups legitimately differ), collects every attribute
    STORE on a ``cfg`` chain package-wide (keys the code itself creates), and
    flags Load chains that resolve against neither."""

    name = "cfg-key-unresolved"
    severity = "warning"
    doc = "cfg.<group>.<key> access that resolves in no composed config"

    # dict/dotdict methods that terminate a chain without naming a config key
    _METHODS = {
        "get", "keys", "items", "values", "pop", "setdefault", "update", "copy",
        "as_dict", "clear",
    }

    def __init__(self, union_tree: Optional[Dict[str, Any]] = None) -> None:
        self._union_override = union_tree

    def _compose_union(self, package) -> Optional[Dict[str, Any]]:
        if self._union_override is not None:
            return self._union_override
        configs_dir = package.root / "sheeprl_tpu" / "configs"
        if not configs_dir.is_dir():
            return None
        try:
            from sheeprl_tpu.config.composer import Composer
        except Exception:
            return None
        composer = Composer()
        union: Dict[str, Any] = {}

        def merge(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
            for k, v in src.items():
                if isinstance(v, dict):
                    node = dst.setdefault(k, {})
                    if isinstance(node, dict):
                        merge(node, v)
                else:
                    dst.setdefault(k, v if v is not None else True)

        composed_any = False
        for exp in composer.available("exp"):
            overrides = [f"exp={exp}", "run_name=lint", "env.id=lint"]
            cfg = None
            # mandatory `???` values (the finetuning exps' exploration_ckpt_path)
            # abort composition; fill each one reported and retry so those exps
            # still contribute their key tree to the union
            for _attempt in range(6):
                try:
                    cfg = composer.compose(overrides)
                    break
                except Exception as exc:
                    msg = str(exc)
                    m = re.search(r"mandatory config value ([\w.]+) is not set", msg)
                    if m is None:
                        break
                    overrides = overrides + [f"{m.group(1)}=lint"]
            if cfg is None:
                continue
            composed_any = True
            merge(union, dict(cfg))
        return union if composed_any else None

    def _stored_paths(self, package) -> Set[str]:
        stored: Set[str] = set()
        for module in package.modules:
            for node in ast.walk(module.tree):
                target: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        target = t
                        path = self._chain(target, require_ctx=None)
                        if path:
                            stored.add(path)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    path = self._chain(node.target, require_ctx=None)
                    if path:
                        stored.add(path)
        return stored

    def _chain(self, node: ast.AST, require_ctx=ast.Load) -> Optional[str]:
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not (isinstance(cur, ast.Name) and cur.id == "cfg" and parts):
            return None
        parts = list(reversed(parts))
        # chains ending in a dict method name a PARENT key only
        while parts and parts[-1] in self._METHODS:
            parts.pop()
        if not parts:
            return None
        return ".".join(parts)

    def run(self, package) -> Iterator[Finding]:
        union = self._compose_union(package)
        if union is None:
            return
        stored = self._stored_paths(package)
        for module in package.modules:
            if "cfg." not in module.source:
                continue
            _set_parents(module.tree)
            reported: Set[Tuple[int, str]] = set()
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Attribute) or not isinstance(node.ctx, ast.Load):
                    continue
                parent = getattr(node, "_lint_parent", None)
                if isinstance(parent, ast.Attribute):
                    continue  # only the maximal chain
                path = self._chain(node)
                if path is None:
                    continue
                segments = path.split(".")
                cursor: Any = union
                resolved: List[str] = []
                for seg in segments:
                    if not isinstance(cursor, dict):
                        break  # below a leaf value: out of the YAML tree's scope
                    if seg in cursor:
                        cursor = cursor[seg]
                        resolved.append(seg)
                        continue
                    if not resolved:
                        # unknown top-level attr (cfg.checkpoint_path, cfg.serve):
                        # runtime-built roots the eval/serve tiers assemble in
                        # code — the rule's claim is about <group>.<key> drift,
                        # which needs a group the YAML tree actually knows
                        break
                    missing_path = ".".join(resolved + [seg])
                    if any(
                        s == missing_path or s.startswith(missing_path + ".")
                        for s in stored
                    ):
                        break  # the code itself creates this key somewhere
                    key = (node.lineno, missing_path)
                    if key not in reported:
                        reported.add(key)
                        yield self.finding(
                            module,
                            node,
                            f"cfg.{missing_path} resolves in none of the composed "
                            "configs and is never assigned in code — config/code "
                            "drift",
                            "fix the key, add it to the config group's YAML, or "
                            "waive with a reason if it is created dynamically",
                        )
                    break


def default_rules() -> List[Rule]:
    return [
        JaxDevicesRule(),
        PlatformDependentGateRule(),
        PallasDotPrecisionRule(),
        AsarrayDonationRule(),
        HostSyncInJitRule(),
        TelemetryEventSchemaRule(),
        LoopHooksRule(),
        CfgKeyResolvesRule(),
    ]


ALL_RULES = default_rules
