"""The lint engine: parse the package once, run the rule catalog, apply waivers.

``run_lint`` is the library surface (the tests and ``bench.py``/``fleet`` call
it); ``lint_main`` is ``python sheeprl.py lint``:

.. code-block:: text

    python sheeprl.py lint                      # human report, exit 0
    python sheeprl.py lint --fail-on warning    # CI gate: unwaived warning+ fails
    python sheeprl.py lint --aot                # + the AOT program-contract sweep
    python sheeprl.py lint --json               # machine-readable report on stdout

The engine itself imports no jax and runs in a few seconds (most of it the
``cfg-key-unresolved`` rule composing every experiment config); ``--aot``
builds and lowers every registered fused program (seconds to minutes — the
same work the tier-1 AOT tests do).
"""

from __future__ import annotations

import ast
import json
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from sheeprl_tpu.analysis.rules import SEVERITIES, Rule, default_rules
from sheeprl_tpu.analysis.waivers import apply_waivers, load_waivers

Finding = Dict[str, Any]

_SEVERITY_RANK = {name: i for i, name in enumerate(SEVERITIES)}


class SourceModule:
    """One parsed source file. Parsing is lazy and cached; a file with a syntax
    error yields a synthetic ``parse-error`` finding instead of crashing the
    whole lint run."""

    def __init__(self, path: Path, rel: str) -> None:
        self.path = path
        self.rel = rel
        self._source: Optional[str] = None
        self._tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None

    @property
    def source(self) -> str:
        if self._source is None:
            self._source = self.path.read_text()
        return self._source

    @property
    def tree(self) -> ast.AST:
        if self._tree is None:
            try:
                self._tree = ast.parse(self.source, filename=str(self.path))
            except SyntaxError as err:
                self.parse_error = err
                self._tree = ast.parse("")
        return self._tree


class Package:
    """The walked package: every ``*.py`` under ``root/sheeprl_tpu`` (or an
    explicit subtree for fixture tests), indexed by repo-relative path."""

    def __init__(self, root: Path, package_dir: Optional[Path] = None) -> None:
        self.root = Path(root)
        package_dir = package_dir or (self.root / "sheeprl_tpu")
        self.modules: List[SourceModule] = []
        self._by_rel: Dict[str, SourceModule] = {}
        if package_dir.is_dir():
            for path in sorted(package_dir.rglob("*.py")):
                rel = path.relative_to(self.root).as_posix()
                module = SourceModule(path, rel)
                self.modules.append(module)
                self._by_rel[rel] = module

    def module(self, rel: str) -> Optional[SourceModule]:
        return self._by_rel.get(rel)


def repo_root() -> Path:
    """The checkout root: the directory holding the ``sheeprl_tpu`` package."""
    return Path(__file__).resolve().parent.parent.parent


def run_lint(
    root: Optional[str] = None,
    rules: Optional[Sequence[Rule]] = None,
    waivers_path: Optional[str] = None,
    use_waivers: bool = True,
) -> Dict[str, Any]:
    """Walk the package, run the rules, apply the waiver file.

    Returns ``{"findings", "waived", "unused_waivers", "rules_run",
    "counts"}`` — ``findings`` are the ACTIVE (unwaived) ones, most severe
    first. Pass ``use_waivers=False`` to see the raw catalog output."""
    package = Package(Path(root) if root else repo_root())
    rules = list(rules) if rules is not None else default_rules()

    raw: List[Finding] = []
    for module in package.modules:
        module.tree  # force the parse so parse errors surface deterministically
        if module.parse_error is not None:
            raw.append(
                {
                    "rule": "parse-error",
                    "severity": "critical",
                    "file": module.rel,
                    "line": int(module.parse_error.lineno or 0),
                    "summary": f"file does not parse: {module.parse_error.msg}",
                    "suggestion": "fix the syntax error; every other rule skipped this file",
                }
            )
    for rule in rules:
        raw.extend(rule.run(package))

    waivers = load_waivers(waivers_path) if use_waivers else []
    active, waived, unused = apply_waivers(raw, waivers)
    # aot-contract waivers can only match when the AOT sweep runs (lint --aot,
    # the tier-1 sweep test) — a static-only pass must not misread them as
    # stale; lint_main's --aot branch judges their staleness instead
    unused = [w for w in unused if w["rule"] != "aot-contract"]
    for w in unused:
        # a stale waiver is itself a finding: it no longer waives anything and
        # should be deleted (or its rule/file/line corrected)
        active.append(
            {
                "rule": "stale-waiver",
                "severity": "warning",
                "file": w["file"],
                "line": int(w.get("line", 0) or 0),
                "summary": f"waiver for rule {w['rule']!r} matches no finding "
                f"(reason was: {w['reason']})",
                "suggestion": "delete the stale entry from analysis/waivers.toml",
            }
        )

    active.sort(key=lambda f: (_SEVERITY_RANK.get(f["severity"], 9), f["file"], f["line"]))
    counts = {sev: sum(1 for f in active if f["severity"] == sev) for sev in SEVERITIES}
    return {
        "findings": active,
        "waived": waived,
        "unused_waivers": unused,
        "rules_run": [r.name for r in rules],
        "counts": counts,
    }


def lint_summary(report: Dict[str, Any]) -> Dict[str, Any]:
    """The compact code-health fingerprint ``bench.py`` and the fleet runner
    attach: {findings, waived, rules_run}."""
    return {
        "findings": len(report["findings"]),
        "waived": len(report["waived"]),
        "rules_run": list(report["rules_run"]),
    }


def _severity_gate(findings: Sequence[Finding], fail_on: Optional[str]) -> int:
    if not fail_on:
        return 0
    threshold = _SEVERITY_RANK[fail_on]
    return 1 if any(_SEVERITY_RANK.get(f["severity"], 9) <= threshold for f in findings) else 0


def _print_report(report: Dict[str, Any], aot: Optional[Dict[str, Any]]) -> None:
    findings = report["findings"]
    print(f"graftlint: {len(report['rules_run'])} rules over the package", end="")
    if aot is not None:
        print(f" + AOT sweep over {aot['programs']} registered programs", end="")
    print()
    for f in findings:
        loc = f"{f['file']}:{f['line']}" if f.get("line") else f["file"]
        print(f"  [{f['severity']:>8}] {f['rule']}: {loc}")
        print(f"             {f['summary']}")
        if f.get("suggestion"):
            print(f"             -> {f['suggestion']}")
    waived = report["waived"]
    if waived:
        print(f"  ({len(waived)} finding(s) waived by analysis/waivers.toml)")
    if not findings:
        print("  no unwaived findings")
    counts = ", ".join(f"{v} {k}" for k, v in report["counts"].items() if v)
    print(f"graftlint: {len(findings)} finding(s){' (' + counts + ')' if counts else ''}")


def lint_main(argv: Optional[Sequence[str]] = None) -> int:
    """``python sheeprl.py lint [--aot] [--json] [--fail-on warning|critical]
    [--no-waivers]``."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="sheeprl.py lint",
        description="JAX-aware static analysis + AOT program-contract gate "
        "(howto/static_analysis.md)",
    )
    parser.add_argument(
        "--aot",
        action="store_true",
        help="also run the AOT contract sweep over every registered fused program "
        "(lowers each for cpu+tpu on the host mesh; needs jax)",
    )
    parser.add_argument("--json", action="store_true", help="machine-readable report on stdout")
    parser.add_argument(
        "--fail-on",
        choices=["warning", "critical"],
        default=None,
        help="exit 1 when any unwaived finding at (or above) this severity exists",
    )
    parser.add_argument(
        "--no-waivers", action="store_true", help="ignore analysis/waivers.toml (raw catalog output)"
    )
    args = parser.parse_args(list(argv) if argv is not None else sys.argv[1:])

    report = run_lint(use_waivers=not args.no_waivers)
    aot_summary: Optional[Dict[str, Any]] = None
    if args.aot:
        from sheeprl_tpu.analysis.programs import aot_sweep

        aot_findings, programs_run = aot_sweep()
        waivers = [] if args.no_waivers else load_waivers()
        active, waived, unused = apply_waivers(aot_findings, waivers)
        # only NOW can an aot-contract waiver's staleness be judged (run_lint
        # deliberately skipped them — they cannot match static findings)
        for w in unused:
            if w["rule"] == "aot-contract":
                active.append(
                    {
                        "rule": "stale-waiver",
                        "severity": "warning",
                        "file": w["file"],
                        "line": int(w.get("line", 0) or 0),
                        "summary": f"waiver for rule {w['rule']!r} matches no finding "
                        f"(reason was: {w['reason']})",
                        "suggestion": "delete the stale entry from analysis/waivers.toml",
                    }
                )
        report["findings"].extend(active)
        report["waived"].extend(waived)
        for f in active:
            report["counts"][f["severity"]] = report["counts"].get(f["severity"], 0) + 1
        report["rules_run"].append("aot-contract")
        aot_summary = {"programs": programs_run, "violations": len(active)}
        report["aot"] = aot_summary

    if args.json:
        print(json.dumps(report, indent=2))
    else:
        _print_report(report, aot_summary)
    return _severity_gate(report["findings"], args.fail_on)
