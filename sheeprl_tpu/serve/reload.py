"""Hot weight reload: a long-lived server picks up newer weights, in place.

A production policy server outlives any single checkpoint — training keeps
publishing newer ones (MindSpeed RL makes the continuous train→serve weight
flow the unit of production RL). This module closes that loop for
``sheeprl.py serve``: a reload thread polls a weight *source*, stages the new
params device-side, validates them, and hands them to
:meth:`~sheeprl_tpu.serve.server.PolicyServer.update_params` — the tick loop
swaps them in atomically *between* ticks. Because the slot-table programs take
params as an ordinary argument, same avals ⇒ the SAME compiled ``slot_step``
program: a reload costs zero recompiles, and no session's device carry is
touched (state and weights are independent inputs — the O(1) session-state
design is what makes the in-place swap safe).

Two sources:

- :class:`CheckpointReloadSource` — watch a run/checkpoint directory through
  the crash supervisor's discovery rules (``resolve_checkpoint_path``
  semantics: manifest-validated, sha256-verified, torn sets can never
  resolve). The ``serve.reload.source=checkpoint`` mode: point a server at the
  run dir it was launched from and it follows training's checkpoint cadence.
- :class:`SubscriberReloadSource` — ride the fleet experience plane's
  versioned weight flow (``data/service.py`` ``WeightSubscriber``): the
  learner publishes, servers refresh — the same plane the actors use.

Safety: a candidate that fails integrity validation (torn file, sha mismatch,
unpicklable payload) or whose params avals do not match the serving policy's
is REJECTED — the old params keep serving, the rejection lands as a ``reload``
event (``status=rejected``) and in the window's ``serve.weights.failures``
counter, and the ``reload_stall`` detector surfaces a reload path that keeps
failing while newer versions exist. The ``reload_torn`` fault
(``resilience/faults.py``) tears the next candidate on disk to exercise
exactly this path deterministically.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

__all__ = [
    "CheckpointReloadSource",
    "ReloadRejected",
    "SubscriberReloadSource",
    "WeightReloader",
    "params_aval_mismatch",
]


class ReloadRejected(RuntimeError):
    """A reload candidate failed validation; the old params keep serving."""


def params_aval_mismatch(current: Any, candidate: Any) -> Optional[str]:
    """None when ``candidate`` has exactly the avals of ``current`` (same tree
    structure, same leaf shapes and dtypes) — the precondition for a zero-
    recompile swap; otherwise a human-readable description of the first
    mismatch. An aval change is a DIFFERENT program (a resized model, a wrong
    checkpoint) and must be rejected, not silently recompiled mid-serve."""
    import jax
    import numpy as np

    cur_leaves, cur_def = jax.tree_util.tree_flatten(current)
    cand_leaves, cand_def = jax.tree_util.tree_flatten(candidate)
    if cur_def != cand_def:
        return f"params tree structure changed: {cand_def} != {cur_def}"
    for i, (a, b) in enumerate(zip(cur_leaves, cand_leaves)):
        a_shape = tuple(np.shape(a))
        b_shape = tuple(np.shape(b))
        if a_shape != b_shape:
            return f"leaf {i} shape changed: {b_shape} != {a_shape}"
        a_dtype = np.asarray(a).dtype if not hasattr(a, "dtype") else a.dtype
        b_dtype = np.asarray(b).dtype if not hasattr(b, "dtype") else b.dtype
        if np.dtype(a_dtype) != np.dtype(b_dtype):
            return f"leaf {i} dtype changed: {b_dtype} != {a_dtype}"
    return None


class CheckpointReloadSource:
    """Follow the newest valid checkpoint under a directory (or an exact file's
    parent): discovery-validated resolution, family extractor for the params.

    Versions are this source's own monotonic counter (1 per successfully
    loaded NEW path) — checkpoint steps order within a run, but the serving
    version axis must survive restarts and resumes, so the counter is local.
    """

    name = "checkpoint"

    def __init__(self, watch_dir: str, fabric: Any, cfg: Any, current_path: Optional[str] = None) -> None:
        self.watch_dir = str(watch_dir)
        self.fabric = fabric
        self.cfg = cfg
        # the checkpoint the server booted from never re-applies as version 1
        self._last_path = os.path.abspath(current_path) if current_path else None
        self._version = 0
        # one-shot scan handoff: the reloader calls peek_available() then
        # poll() back to back each poll — share a single directory resolution
        # (each scan re-validates candidates) instead of scanning twice
        self._scan: Optional[Tuple[Optional[str]]] = None

    def peek_available(self) -> Optional[int]:
        """Whether an unapplied candidate exists (versions-available probe for
        the stall accounting): the source's NEXT version when a newer path is
        resolvable, else the current one."""
        from sheeprl_tpu.resilience.discovery import find_latest_checkpoint

        self._scan = None
        newest = find_latest_checkpoint(self.watch_dir)
        self._scan = (newest,)
        if newest is not None and os.path.abspath(newest) != self._last_path:
            return self._version + 1
        return self._version

    def poll(self) -> Optional[Tuple[Any, int, Dict[str, Any]]]:
        """(params, version, meta) when a NEW valid checkpoint resolved, None
        when nothing newer exists. Raises :class:`ReloadRejected` when the
        candidate is torn/unloadable — the caller keeps the old params."""
        from sheeprl_tpu.resilience import faults
        from sheeprl_tpu.resilience.discovery import (
            checkpoint_step,
            find_latest_checkpoint,
            is_valid_checkpoint,
        )

        scan, self._scan = self._scan, None
        newest = scan[0] if scan is not None else find_latest_checkpoint(self.watch_dir)
        if newest is None or os.path.abspath(newest) == self._last_path:
            return None
        if faults.consume_reload_torn():
            _tear_checkpoint(newest)
            if not is_valid_checkpoint(newest):
                raise ReloadRejected(
                    f"torn checkpoint rejected by integrity validation: {newest}"
                )
        try:
            params = self._extract_params(newest)
        except ReloadRejected:
            raise
        except Exception as exc:
            raise ReloadRejected(f"checkpoint {newest} failed to load: {exc!r}") from exc
        self._last_path = os.path.abspath(newest)
        self._version += 1
        return params, self._version, {
            "path": newest,
            "checkpoint_step": checkpoint_step(newest),
        }

    def _extract_params(self, path: str) -> Any:
        """Run the SAME family extractor the serve boot ran — the params of the
        new checkpoint in serving form (the step functions are discarded; only
        the params swap)."""
        from sheeprl_tpu.serve.policy import resolve_serve_policy
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        state = load_checkpoint(path)
        return resolve_serve_policy(self.fabric, self.cfg, state).params


class SubscriberReloadSource:
    """Ride the fleet weight plane: the versioned, immutable, GC'd payloads of
    ``data/service.py``'s ``WeightPublisher``/``WeightSubscriber``. The plane's
    own version numbers ARE the serving versions."""

    name = "subscriber"

    def __init__(self, subscriber: Any) -> None:
        self.subscriber = subscriber

    def peek_available(self) -> Optional[int]:
        return int(self.subscriber.peek_latest())

    def poll(self) -> Optional[Tuple[Any, int, Dict[str, Any]]]:
        from sheeprl_tpu.resilience import faults

        if faults.consume_reload_torn():
            # the plane's payloads are immutable, so a torn read manifests as
            # an undecodable tree — emulate with a poisoned payload
            payload = self.subscriber.poll()
            if payload is not None:
                raise ReloadRejected(
                    f"torn weight payload rejected (version {payload.get('version')})"
                )
            return None
        payload = self.subscriber.poll()
        if payload is None:
            return None
        return payload["tree"], int(payload["version"]), {"final": payload.get("final")}


def _tear_checkpoint(path: str) -> None:
    """Corrupt ``path`` on disk the way a mid-write kill would (``reload_torn``
    fault): a pickle file is truncated to half, an orbax dir loses its sidecar's
    integrity by truncating the extras pickle."""
    target = path if os.path.isfile(path) else path + ".extras.pkl"
    try:
        size = os.path.getsize(target)
        with open(target, "r+b") as fh:
            fh.truncate(max(size // 2, 1))
    except OSError:
        pass


class WeightReloader:
    """The reload thread: poll the source at ``poll_s``, stage candidate params
    on the serving device, validate avals, hand them to the server. All
    telemetry rides :class:`~sheeprl_tpu.serve.telemetry.ServingTelemetry`
    (``reload`` events + the windows' ``serve.weights`` block)."""

    def __init__(
        self,
        server: Any,
        source: Any,
        *,
        telemetry: Any = None,
        poll_s: float = 2.0,
        device: Any = None,
    ) -> None:
        self.server = server
        self.source = source
        self.telemetry = telemetry
        self.poll_s = max(float(poll_s), 0.05)
        self.device = device
        self.applied = 0
        self.failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------------

    def start(self) -> "WeightReloader":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="sheeprl-serve-reload", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        last_reason: Optional[str] = None
        while not self._stop.wait(self.poll_s):
            try:
                self.step()
                last_reason = None
            except Exception as exc:
                # the reload thread must never take the server down — but a
                # broken source (unmounted watch_dir, malformed payload) must
                # leave a failure trail for the reload_stall detector instead
                # of serving stale weights with failures=0. A repeat of the
                # same failure bumps the counter quietly (no event per poll).
                reason = f"{type(exc).__name__}: {exc}"
                self.failures += 1
                if self.telemetry is not None:
                    self.telemetry.observe_reload(
                        failed=True,
                        reason=reason,
                        source=getattr(self.source, "name", None),
                        quiet=(reason == last_reason),
                    )
                last_reason = reason

    # -- one poll (directly drivable from tests) -----------------------------------

    def step(self) -> Optional[int]:
        """One reload poll: returns the staged version on success, None when
        there was nothing new or the candidate was rejected."""
        from sheeprl_tpu.serve.server import ServerClosed

        available = None
        try:
            available = self.source.peek_available()
        except Exception:
            pass
        if available and self.telemetry is not None:
            self.telemetry.observe_reload(available=int(available))

        try:
            candidate = self.source.poll()
        except ReloadRejected as exc:
            self.failures += 1
            if self.telemetry is not None:
                self.telemetry.observe_reload(
                    failed=True, reason=str(exc), source=getattr(self.source, "name", None)
                )
            return None
        if candidate is None:
            return None
        params, version, _meta = candidate

        mismatch = params_aval_mismatch(self.server.policy.params, params)
        if mismatch is not None:
            self.failures += 1
            if self.telemetry is not None:
                self.telemetry.observe_reload(
                    failed=True,
                    reason=f"aval mismatch: {mismatch}",
                    source=getattr(self.source, "name", None),
                )
            return None

        staged = self._stage(params)
        try:
            self.server.update_params(staged, version)
        except ServerClosed:
            return None
        self.applied += 1
        return int(version)

    def _stage(self, params: Any) -> Any:
        """Move the candidate tree onto the serving device BEFORE the swap is
        staged, so the tick loop's rebind is instant (no host→device transfer
        on the serving path). Placement stays UNCOMMITTED (``device_put`` with
        no device) unless an explicit device was configured: the boot params
        are uncommitted, and a committed swap would change the jit argument
        signature — recompiling step/attach at the first post-swap call, which
        breaks the zero-recompile contract."""
        import jax

        try:
            if self.device is not None:
                return jax.device_put(params, self.device)
            return jax.device_put(params)
        except Exception:
            return params
