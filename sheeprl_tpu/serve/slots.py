"""Device-resident session-slot table: O(1) per-step state, updated in place.

The compiler-first O(1) autoregressive-caching argument (PAPERS.md, arxiv
2603.09555) applied to GRU/RSSM policies: each concurrent session owns one row
of a fixed-size slot table whose state pytree lives on-device with a leading
``[S]`` slot axis. ONE donated, fixed-shape jitted program

    step(params, slot_states, slot_obs, slot_mask) -> (actions, slot_states')

advances every pending session per tick — the donated ``slot_states`` buffers
are updated in place (XLA input/output aliasing), so steady-state serving moves
only observations in and actions out across the host↔device boundary; session
state NEVER crosses it. Masked slots (inactive, or active but without a pending
request this tick) keep their carry bit-exact via a ``where`` — no gather, no
scatter, no shape change, hence no recompile, ever.

Admission is the same trick: ``attach(params, states, keys, mask)`` writes
freshly initialized carries into the masked slots between steps (one fixed-shape
donated program for ANY subset of slots), so sessions attach and evict without
touching the step program.

Per-slot PRNG keys ride inside the carry (``ServePolicy.init_slot``), which
makes every session's action stream a pure function of (params, seed, obs
sequence) — batch composition cannot perturb it. That is the property the
serving parity tests pin (tests/test_serve/test_policies.py).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.serve.policy import ServePolicy

__all__ = ["SlotTable"]


def _mask_select(mask: jax.Array):
    """tree_map-able ``where`` over the slot axis: mask [S] broadcast against
    arbitrary-rank leaves."""

    def sel(new, old):
        m = mask.reshape(mask.shape[0], *([1] * (new.ndim - 1)))
        return jnp.where(m, new, old)

    return sel


class SlotTable:
    """S device-resident session slots + the donated step/attach programs.

    Host-side bookkeeping (which session holds which slot) is plain Python —
    the device programs only ever see the fixed ``[S]`` shapes. Not thread-safe
    by itself; the server serializes access through its tick loop.
    """

    def __init__(self, policy: ServePolicy, num_slots: int, base_seed: int = 0) -> None:
        if num_slots < 1:
            raise ValueError(f"serve.slots must be >= 1, got {num_slots}")
        self.policy = policy
        self.num_slots = int(num_slots)
        self.base_seed = int(base_seed)

        vstep = jax.vmap(policy.step_slot, in_axes=(None, 0, 0))
        vinit = jax.vmap(policy.init_slot, in_axes=(None, 0))

        def _step(params, states, obs, mask):
            actions, new_states = vstep(params, states, obs)
            new_states = jax.tree_util.tree_map(_mask_select(mask), new_states, states)
            return actions, new_states

        def _attach(params, states, keys, mask):
            fresh = vinit(params, keys)
            return jax.tree_util.tree_map(_mask_select(mask), fresh, states)

        # donation: the slot-state buffers are reused in place every tick — the
        # table's state footprint is O(S), not O(S * ticks); callers rebind to
        # the returned tree so the invalidated inputs are never read again
        self._step = jax.jit(_step, donate_argnums=(1,))
        self._attach = jax.jit(_attach, donate_argnums=(1,))
        self._vinit = jax.jit(vinit)

        keys = self._slot_keys(self.base_seed + i for i in range(self.num_slots))
        self.states = self._vinit(policy.params, keys)
        # fixed-shape table: the state footprint is a CONSTANT after init (no
        # recompiles, no shape changes) — computed once, never on the tick path
        self._state_bytes = sum(
            int(leaf.nbytes)
            for leaf in jax.tree_util.tree_leaves(self.states)
            if hasattr(leaf, "nbytes")
        )
        self._free: List[int] = list(range(self.num_slots))
        self._owner: Dict[int, Any] = {}  # slot -> opaque session handle
        self._lock = threading.Lock()

    # -- host bookkeeping ----------------------------------------------------------

    def _slot_keys(self, seeds) -> jax.Array:
        return jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])

    @property
    def free_slots(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def active_slots(self) -> int:
        with self._lock:
            return len(self._owner)

    def try_admit(self, session: Any) -> Optional[int]:
        """Claim a free slot for ``session`` (device state still stale until
        :meth:`attach` runs); None when the table is full."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop(0)
            self._owner[slot] = session
            return slot

    def evict(self, slot: int) -> None:
        """Release ``slot``. The stale device carry is left in place — the next
        admission's :meth:`attach` overwrites it, so eviction is free."""
        with self._lock:
            self._owner.pop(slot, None)
            if slot not in self._free:
                self._free.append(slot)

    # -- device programs -----------------------------------------------------------

    def attach(self, slot_seeds: Dict[int, int]) -> None:
        """Initialize the carries of ``slot_seeds``'s slots (slot -> session
        seed) in ONE fixed-shape donated program — any subset, no recompile."""
        if not slot_seeds:
            return
        mask = np.zeros((self.num_slots,), np.bool_)
        seeds = [0] * self.num_slots
        for slot, seed in slot_seeds.items():
            mask[slot] = True
            seeds[slot] = int(seed)
        keys = self._slot_keys(seeds)
        self.states = self._attach(self.policy.params, self.states, keys, jnp.asarray(mask))

    def step(self, obs: Dict[str, np.ndarray], mask: np.ndarray) -> np.ndarray:
        """One serving tick: ``obs`` are ``[S, ...]`` host arrays (zeros in
        masked-out rows), ``mask`` the pending-request slots. Returns the
        ``[S, ...]`` action array (masked rows carry garbage — the caller only
        reads rows it asked for)."""
        actions, self.states = self._step(
            self.policy.params, self.states, obs, jnp.asarray(mask)
        )
        return np.asarray(actions)

    # -- introspection -------------------------------------------------------------

    def state_bytes(self) -> int:
        """Device bytes the whole slot table holds — the O(S) session-state
        footprint reported in serving telemetry (constant; cached at init)."""
        return self._state_bytes

    def aot_programs(self) -> Tuple[Any, Any]:
        """The (step, attach) jitted callables for AOT lowering/priming — the
        TPU-readiness tests lower exactly what serving runs."""
        return self._step, self._attach


# ---- AOT contract registration (sheeprl_tpu/analysis/programs.py) -------------
# The serving acceptance gate as registry entries: the donated step program
# (slot-state aliasing in MLIR, input_output_alias in optimized HLO, no host
# callbacks — steady-state serving moves only obs in / actions out) and the
# fixed-shape attach program, built over a deterministic toy recurrent policy.

from sheeprl_tpu.analysis.programs import register_fused_program  # noqa: E402


def _aot_table() -> "SlotTable":
    from sheeprl_tpu.serve.policy import ObsSpec, ServePolicy

    params = {"w": jnp.ones((3,))}

    def init_slot(params, key):
        return {"acc": jnp.zeros((3,)), "key": key}

    def step_slot(params, carry, obs):
        acc = carry["acc"] + obs["state"].astype(jnp.float32)
        key, _ = jax.random.split(carry["key"])
        return (acc * params["w"]).sum(), {"acc": acc, "key": key}

    policy = ServePolicy(
        algo="counter",
        params=params,
        init_slot=init_slot,
        step_slot=step_slot,
        obs_spec={"state": ObsSpec((3,), np.float32)},
        action_shape=(),
    )
    return SlotTable(policy, 4)


@register_fused_program(
    "serve.slot_step",
    compile_on_cpu=True,
    doc="donated fixed-shape serving tick over the device-resident slot table",
)
def _aot_slot_step():
    table = _aot_table()
    step, _attach = table.aot_programs()
    obs = {"state": np.zeros((table.num_slots, 3), np.float32)}
    mask = np.zeros((table.num_slots,), np.bool_)
    return step, (table.policy.params, table.states, obs, mask)


@register_fused_program(
    "serve.slot_attach",
    compile_on_cpu=True,
    doc="donated fixed-shape session-admission program (masked carry init)",
)
def _aot_slot_attach():
    table = _aot_table()
    _step, attach = table.aot_programs()
    keys = table._slot_keys([0] * table.num_slots)
    mask = np.zeros((table.num_slots,), np.bool_)
    return attach, (table.policy.params, table.states, keys, mask)
