"""Serving-run drivers: real env sessions (CLI smoke) and open-loop load.

Both are plain clients of :class:`~sheeprl_tpu.serve.server.PolicyServer` —
the server never knows whether a session is a gymnasium episode, a synthetic
load generator, or (eventually) a network frontend.

- :func:`run_env_sessions` — ``serve.sessions=N`` mode: N concurrent client
  threads each play a real environment episode end-to-end with served actions
  (the "millions of users" traffic pattern shrunk to a CPU smoke). Returns the
  per-session action streams, which the parity tests compare against a
  sequential reference.
- :func:`run_synthetic_load` — the ``serve_load`` bench workload: an open-loop
  session generator (arrivals do not wait for completions) pushing
  fixed-length sessions of random observations through the server, measuring
  sessions/sec and per-step latency percentiles under genuine concurrency.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.serve.server import (
    DeadlineExceeded,
    PolicyServer,
    ServerClosed,
    ServerOverloaded,
)

__all__ = ["run_env_sessions", "run_synthetic_load"]

# env-driver client etiquette under overload: honor the shed's retry-after a
# bounded number of times, retry a deadline-missed request once per step
_ADMISSION_RETRIES = 8
_DEADLINE_RETRIES = 2


def _open_with_retry(server: PolicyServer, seed: int, record: Dict[str, Any]):
    """A WELL-BEHAVED client of the overload-protection plane: a shed admission
    waits the server's ``retry_after_s`` hint and retries (bounded) instead of
    hammering; the retry count rides the session record."""
    for _ in range(_ADMISSION_RETRIES):
        try:
            return server.open_session(seed=seed)
        except ServerOverloaded as exc:
            record["admission_retries"] = record.get("admission_retries", 0) + 1
            time.sleep(min(exc.retry_after_s, 5.0))
    return server.open_session(seed=seed)  # last try: let the rejection surface


def run_env_sessions(
    server: PolicyServer,
    cfg: Any,
    *,
    sessions: int,
    max_session_steps: int = 1000,
    log_dir: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Drive ``sessions`` concurrent env episodes through the server; returns
    one record per session: ``{seed, steps, reward, actions, error}``."""
    from sheeprl_tpu.utils.env import make_env

    results: List[Dict[str, Any]] = [{} for _ in range(sessions)]

    def _client(i: int) -> None:
        record: Dict[str, Any] = {"seed": int(cfg.seed) + i, "steps": 0, "reward": 0.0, "actions": []}
        results[i] = record
        env = None
        session = None
        # env feedback for the last served action that has not yet been fed
        # back: (reward, next_obs, terminated) — the trajectory-capture
        # plane completes that transition on the NEXT step (or at close)
        feedback = None
        try:
            env = make_env(cfg, record["seed"], i, log_dir, "serve", vector_env_idx=i)()
            session = _open_with_retry(server, record["seed"], record)
            obs = env.reset(seed=record["seed"])[0]
            for _ in range(max_session_steps):
                for attempt in range(_DEADLINE_RETRIES + 1):
                    try:
                        action = session.step(
                            obs, reward=feedback[0] if feedback is not None else None
                        )
                        feedback = None
                        break
                    except DeadlineExceeded:
                        # the request never reached the device (carry intact):
                        # retrying the SAME observation preserves the episode
                        record["deadline_retries"] = record.get("deadline_retries", 0) + 1
                        if attempt >= _DEADLINE_RETRIES:
                            raise
                record["actions"].append(np.asarray(action))
                obs, reward, terminated, truncated, _ = env.step(
                    np.asarray(action).reshape(env.action_space.shape)
                )
                feedback = (reward, obs, bool(terminated))
                record["reward"] += float(np.asarray(reward))
                record["steps"] += 1
                if bool(terminated) or bool(truncated):
                    break
        except (ServerClosed, ServerOverloaded, DeadlineExceeded, TimeoutError) as exc:
            record["error"] = repr(exc)
        finally:
            if session is not None:
                if feedback is not None:
                    session.close(
                        reward=feedback[0],
                        next_obs=feedback[1],
                        terminated=feedback[2],
                    )
                else:
                    session.close()
            if env is not None:
                env.close()

    threads = [threading.Thread(target=_client, args=(i,), daemon=True) for i in range(sessions)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def run_synthetic_load(
    server: PolicyServer,
    *,
    sessions: int,
    steps_per_session: int,
    arrival_interval_s: float = 0.0,
    seed: int = 0,
) -> Dict[str, Any]:
    """Open-loop load: ``sessions`` synthetic clients arrive on a fixed
    schedule (never gated on completions) and each runs ``steps_per_session``
    random-observation steps. Returns host-side aggregates; the authoritative
    latency/occupancy numbers come from the server's telemetry summary."""
    rng = np.random.default_rng(seed)
    spec = server.policy.obs_spec
    done = threading.Event()
    state = {"finished": 0, "steps": 0, "errors": 0, "shed": 0, "deadline_missed": 0}
    lock = threading.Lock()

    def _client(i: int) -> None:
        session = None
        try:
            session = server.open_session(seed=seed + i)
            obs = {
                k: (rng.integers(0, 255, s.shape).astype(s.dtype)
                    if np.issubdtype(np.dtype(s.dtype), np.integer)
                    else rng.normal(size=s.shape).astype(s.dtype))
                for k, s in spec.items()
            }
            steps = 0
            for _ in range(steps_per_session):
                try:
                    session.step(obs)
                    steps += 1
                except DeadlineExceeded:
                    # open-loop semantics: a missed deadline is counted and the
                    # session moves on — arrivals never slow down for the server
                    with lock:
                        state["deadline_missed"] += 1
            with lock:
                state["finished"] += 1
                state["steps"] += steps
        except ServerOverloaded:
            # shed at admission: open-loop clients do NOT retry — the point of
            # the generator is to measure how the server holds under overload
            with lock:
                state["shed"] += 1
        except (ServerClosed, TimeoutError):
            with lock:
                state["errors"] += 1
        finally:
            # a timed-out session MUST release its slot — a leaked slot shrinks
            # capacity for every later session and cascades the stall
            if session is not None:
                session.close()
            with lock:
                if state["finished"] + state["errors"] + state["shed"] >= sessions:
                    done.set()

    t0 = time.perf_counter()
    for i in range(sessions):
        threading.Thread(target=_client, args=(i,), daemon=True).start()
        if arrival_interval_s > 0:
            time.sleep(arrival_interval_s)
    done.wait()
    wall = time.perf_counter() - t0
    return {
        "sessions": sessions,
        "sessions_finished": state["finished"],
        "errors": state["errors"],
        "sessions_shed": state["shed"],
        "shed_rate": round(state["shed"] / sessions, 4) if sessions else 0.0,
        "deadline_missed": state["deadline_missed"],
        "steps": state["steps"],
        "wall_seconds": round(wall, 3),
        "sessions_per_sec": round(state["finished"] / wall, 3) if wall > 0 else None,
        "steps_per_sec": round(state["steps"] / wall, 3) if wall > 0 else None,
    }
