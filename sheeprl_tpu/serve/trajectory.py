"""Session-trajectory capture: the serving tier's actor half of the flywheel.

``sheeprl.py live`` (howto/live.md) closes the production loop — serving slots
double as actors. This module is the capture plane that makes that possible
without touching the tick loop's latency budget:

- :class:`SessionRecorder` — per-session transition assembly, driven entirely
  by the CLIENT thread (``ServeSession.step``/``close``). A transition is
  ``(obs, action)`` begun when an action is delivered and COMPLETED by the next
  request's ``reward`` (with that request's observation as ``next_obs``); the
  final transition completes at ``close(reward=..., terminated=...)``. A
  session that vanishes mid-request (evicted, shed, drained, crashed client)
  leaves its last transition pending — the recorder drops it and marks the
  preceding completed transition ``truncated``, so an emitted trajectory is
  never torn: it is a contiguous run of complete transitions ending in a
  ``terminated`` or ``truncated`` flag.
- :class:`TrajectoryIngest` — the bounded hand-off between finished sessions
  and the experience plane. ``offer()`` is O(1) and never blocks: a full queue
  sheds the trajectory and counts it (``Serve/trajectories_dropped``, the
  explicit overflow policy of the live subsystem — a slow learner must cost
  training data, never serving latency). A worker thread drains the queue,
  stacks each trajectory into the ``_service_actor`` row format
  (``[T, 1, ...]`` float32 blocks keyed ``observations`` / ``actions`` /
  ``rewards`` / ``terminated`` / ``truncated`` and, for learners that store
  them, ``next_observations``) and ships it through an
  :class:`~sheeprl_tpu.data.service.ExperienceWriter`.

The capture path is exploration-faithful: the recorded action is the action
the CLIENT received (noise included for explore slots), because that is the
action the environment actually saw.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["SessionRecorder", "TrajectoryIngest"]


class SessionRecorder:
    """One session's transition log, thread-confined to its client thread
    (exactly like :class:`~sheeprl_tpu.serve.server.ServeSession` itself)."""

    def __init__(self, ingest: "TrajectoryIngest", seed: int, slot: Optional[int]) -> None:
        self._ingest = ingest
        self.seed = int(seed)
        self.slot = slot
        self._pending: Optional[tuple] = None  # (obs, action) awaiting its reward
        self._transitions: List[Dict[str, Any]] = []
        self._emitted = False

    def begin(self, obs: Any, action: Any) -> None:
        """An action was delivered for ``obs``: open the transition that the
        NEXT request's reward (or ``finish``) will complete."""
        self._pending = (
            {k: np.array(v) for k, v in obs.items()},
            np.array(action),
        )

    def complete(
        self,
        reward: Any,
        *,
        next_obs: Any,
        terminated: bool = False,
        truncated: bool = False,
    ) -> None:
        """Close the pending transition with its environment feedback."""
        if self._pending is None:
            return
        obs, action = self._pending
        self._pending = None
        self._transitions.append(
            {
                "obs": obs,
                "action": action,
                "reward": float(np.asarray(reward).reshape(-1)[0]),
                "next_obs": {k: np.array(v) for k, v in next_obs.items()},
                "terminated": bool(terminated),
                "truncated": bool(truncated),
            }
        )

    def finish(
        self,
        *,
        reward: Any = None,
        next_obs: Any = None,
        terminated: bool = False,
    ) -> None:
        """Session over. With a final ``reward`` the pending transition
        completes as the episode tail (``terminated`` from the env, else
        ``truncated`` — a step-capped or wound-down episode). Without one the
        pending request never got its feedback (evicted / shed / drained /
        client error): it is DROPPED and the previous transition is marked
        ``truncated``, keeping the emitted trajectory whole. Idempotent."""
        if self._emitted:
            return
        self._emitted = True
        if self._pending is not None:
            if reward is not None:
                obs, _ = self._pending
                self.complete(
                    reward,
                    next_obs=next_obs if next_obs is not None else obs,
                    terminated=bool(terminated),
                    truncated=not bool(terminated),
                )
            else:
                self._pending = None
                if self._transitions:
                    self._transitions[-1]["truncated"] = True
                    self._transitions[-1]["terminated"] = False
        elif self._transitions and not (
            self._transitions[-1]["terminated"] or self._transitions[-1]["truncated"]
        ):
            # feedback for the last action arrived via step() but the episode
            # never signalled an end: close it as truncated
            self._transitions[-1]["truncated"] = True
        transitions, self._transitions = self._transitions, []
        if transitions:
            self._ingest.offer(transitions, seed=self.seed)


class TrajectoryIngest:
    """Bounded trajectory queue + assembly worker in front of an
    :class:`~sheeprl_tpu.data.service.ExperienceWriter`.

    ``offer()`` (client threads) sheds on overflow — counted, never blocking;
    the worker thread owns the writer (``ExperienceWriter`` is single-threaded
    by design) and performs all stacking/flattening OFF both the tick loop and
    the client threads' latency paths."""

    def __init__(
        self,
        writer: Any,
        *,
        mlp_keys: Sequence[str],
        max_queue: int = 64,
        sample_next_obs: bool = False,
        telemetry: Any = None,
        weight_version_of: Any = None,
    ) -> None:
        self.writer = writer
        self.mlp_keys = [str(k) for k in mlp_keys]
        self.max_queue = max(int(max_queue), 1)
        self.sample_next_obs = bool(sample_next_obs)
        self.telemetry = telemetry
        # lineage: stamp each shipped block with the policy version that
        # produced it (the server's live weight version) so the learner's
        # weight-lag accounting sees serving traffic like any other actor
        self.weight_version_of = weight_version_of
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._error: Optional[BaseException] = None
        # cumulative counters (lock-protected by _cond)
        self.captured = 0
        self.ingested = 0
        self.dropped = 0
        self.rows = 0
        self._thread = threading.Thread(
            target=self._run, name="sheeprl-traj-ingest", daemon=True
        )
        self._thread.start()

    # -- client-thread side --------------------------------------------------------

    def offer(self, transitions: List[Dict[str, Any]], *, seed: int = 0) -> bool:
        """Hand a finished session's transitions to the worker. O(1), never
        blocks: a full queue drops the trajectory and counts it (the live
        subsystem's explicit shed-don't-stall overflow policy)."""
        dropped = False
        with self._cond:
            self.captured += 1
            if self._closed or len(self._queue) >= self.max_queue:
                self.dropped += 1
                dropped = True
            else:
                self._queue.append((transitions, int(seed)))
                self._cond.notify_all()
        if self.telemetry is not None:
            self.telemetry.observe_trajectories(
                captured=1, dropped=1 if dropped else 0
            )
            # episode return attributed to the weight version serving NOW —
            # the session just closed, so the live version is the one that
            # produced (at least the tail of) this trajectory; feeds the
            # per-version split + the promotion verdict's return check
            observe_episode = getattr(self.telemetry, "observe_episode", None)
            if observe_episode is not None and transitions:
                ended = bool(
                    transitions[-1].get("terminated") or transitions[-1].get("truncated")
                )
                if ended:
                    try:
                        return_ = float(sum(t["reward"] for t in transitions))
                        version = (
                            int(self.weight_version_of())
                            if self.weight_version_of is not None
                            else None
                        )
                        observe_episode(return_, version=version)
                    except Exception:
                        pass  # return accounting must never break capture
        return not dropped

    # -- worker side ---------------------------------------------------------------

    def _flat_obs(self, obs: Dict[str, Any]) -> np.ndarray:
        return np.concatenate(
            [np.asarray(obs[k]).reshape(-1) for k in self.mlp_keys]
        ).astype(np.float32)

    def _assemble(self, transitions: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
        """Stack one trajectory into the experience-service row format: the
        exact ``[T, 1, ...]`` blocks ``_service_actor`` ships (one env column —
        a serving role is one env worth of traffic per session)."""
        rows: Dict[str, np.ndarray] = {
            "observations": np.stack(
                [self._flat_obs(t["obs"]) for t in transitions]
            )[:, np.newaxis, :],
            "actions": np.stack(
                [np.asarray(t["action"], dtype=np.float32).reshape(-1) for t in transitions]
            )[:, np.newaxis, :],
            "rewards": np.asarray(
                [[t["reward"]] for t in transitions], dtype=np.float32
            )[:, np.newaxis, :],
            "terminated": np.asarray(
                [[float(t["terminated"])] for t in transitions], dtype=np.float32
            )[:, np.newaxis, :],
            "truncated": np.asarray(
                [[float(t["truncated"])] for t in transitions], dtype=np.float32
            )[:, np.newaxis, :],
        }
        if not self.sample_next_obs:
            rows["next_observations"] = np.stack(
                [self._flat_obs(t["next_obs"]) for t in transitions]
            )[:, np.newaxis, :]
        return rows

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.1)
                if not self._queue and self._closed:
                    return
                transitions, seed = self._queue.popleft()
            try:
                rows = self._assemble(transitions)
                if self.weight_version_of is not None:
                    self.writer.weight_version = int(self.weight_version_of())
                self.writer.add(rows, steps=None)
                self.writer.flush()
            except BaseException as exc:
                with self._cond:
                    if self._error is None:
                        self._error = exc
                    self.dropped += 1
                if self.telemetry is not None:
                    self.telemetry.observe_trajectories(dropped=1)
                continue
            with self._cond:
                self.ingested += 1
                self.rows += len(transitions)
            if self.telemetry is not None:
                self.telemetry.observe_trajectories(
                    ingested=1, rows=len(transitions)
                )

    # -- lifecycle -----------------------------------------------------------------

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop accepting trajectories, drain what is queued, join the worker.
        Does NOT close the writer — its owner (the live runner) does, so the
        EOS marker can ride the role's ordinary shutdown sequence."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=max(float(timeout_s), 0.0))

    def telemetry_snapshot(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "trajectories_captured": self.captured,
                "trajectories_ingested": self.ingested,
                "trajectories_dropped": self.dropped,
                "trajectory_rows": self.rows,
                "queue_depth": len(self._queue),
            }
