"""Continuous-batching policy server over a device-resident slot table.

Concurrent sessions (each a client thread, ultimately a network frontend)
submit observations; a single tick loop coalesces whatever is pending into one
fixed-shape batched step over the slot table (``serve/slots.py``) and fans the
actions back out. Throughput is bounded by batch occupancy, not by per-session
round-trips — the continuous-batching design of LLM serving applied to
recurrent policy inference:

- **admission**: a new session waits in the queue until a slot frees up, then
  one masked ``attach`` program initializes its device carry *between* steps —
  no recompile, no effect on co-resident sessions;
- **coalescing**: a tick fires as soon as every attached session has a pending
  request, or after ``max_batch_wait_ms`` from the first pending request —
  latency is traded against occupancy with one knob;
- **masking**: sessions that did not submit this tick keep their carry
  bit-exact (the step program ``where``s them out) — a slow client never
  corrupts its own session state;
- **eviction**: closing a session frees its slot immediately; the stale carry
  is overwritten by the next admission.

The robustness plane (howto/serving.md, "Operating a server"):

- **overload shedding** — ``max_queue`` bounds the admission queue; a session
  arriving past it is rejected with :class:`ServerOverloaded` (carrying a
  ``retry_after_s`` hint from the observed session-completion rate) instead of
  queueing unboundedly;
- **deadlines** — ``deadline_ms`` bounds each request: an observation still
  pending past its deadline is dropped *before* the tick (the carry stays
  bit-exact — the request never reached the device) and the client gets
  :class:`DeadlineExceeded`;
- **degraded mode** — under sustained saturation (full table + waiting queue,
  or shedding) the coalescing window widens by ``degraded_wait_factor`` to buy
  occupancy back at a latency cost; it narrows again when saturation clears;
- **hot weight reload** — :meth:`PolicyServer.update_params` stages a new
  params pytree; the tick loop swaps it in atomically *between* steps. Same
  avals ⇒ the SAME compiled step program (params are an ordinary argument) —
  zero recompiles, and no session's carry is touched (the O(1) device-side
  session-state argument: state and weights are independent inputs);
- **graceful drain** — :meth:`begin_drain` stops admissions (queued sessions
  are shed), lets in-flight sessions finish within a grace window, then closes
  with a ``clean_exit`` summary. The SIGTERM path of ``sheeprl.py serve``.

The server is transport-agnostic: :meth:`PolicyServer.open_session` returns an
in-process handle (``session.step(obs) -> action``); the CLI's env driver and
the bench's open-loop generator (``serve/drivers.py``) are both plain clients.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.serve.policy import ServePolicy
from sheeprl_tpu.serve.slots import SlotTable

__all__ = [
    "DeadlineExceeded",
    "PolicyServer",
    "ServeSession",
    "ServerClosed",
    "ServerOverloaded",
]

# degraded-mode hysteresis: consecutive saturated ticks that enter the mode,
# and consecutive healthy ticks that exit it (module constants so tests and
# operators can reason about them)
DEGRADED_ENTER_TICKS = 8
DEGRADED_EXIT_TICKS = 8
DEFAULT_DEGRADED_WAIT_FACTOR = 4.0


class ServerClosed(RuntimeError):
    """The server shut down (or crashed) while a session was waiting on it.
    When the tick loop died, the root-cause exception rides as ``__cause__``
    (and its repr in the message) — clients see WHY, not just that it ended."""


class ServerOverloaded(RuntimeError):
    """Admission was shed: the slot table is full and the bounded admission
    queue (``max_queue``) is too. ``retry_after_s`` is the server's estimate of
    when capacity frees up (from the observed session-completion rate)."""

    def __init__(self, message: str, retry_after_s: float) -> None:
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(RuntimeError):
    """The request's observation was still pending when its ``deadline_ms``
    expired; it was dropped before the tick (the session carry is untouched —
    the request never reached the device) and the client may retry."""


class ServeSession:
    """Client-side handle for one policy session. Thread-confined: one client
    thread drives ``step`` sequentially; concurrency lives ACROSS sessions."""

    def __init__(self, server: "PolicyServer", seed: int) -> None:
        self._server = server
        self.seed = int(seed)
        self.slot: Optional[int] = None
        self.steps = 0
        self._obs: Optional[Dict[str, np.ndarray]] = None
        self._action: Optional[np.ndarray] = None
        self._submit_time = 0.0
        self._attached_time = 0.0
        self._deadline: Optional[float] = None
        self._deadline_missed = False
        self._event = threading.Event()
        self._closed = False
        # trajectory capture (serve/trajectory.py) — created lazily on the
        # first successful step when the server has an ingest plane; explore
        # noise rng is seeded from the SESSION seed at slot attach (purity:
        # the stream depends only on the session, never on co-batching)
        self._recorder: Optional[Any] = None
        self._noise_rng: Optional[np.random.Generator] = None

    def step(
        self,
        obs: Dict[str, np.ndarray],
        timeout: Optional[float] = None,
        *,
        reward: Any = None,
    ) -> np.ndarray:
        """Submit one observation, block until the batched step returns this
        session's action. ``reward`` is the env feedback for the PREVIOUS
        action (with ``obs`` as its next observation) — it completes that
        pending transition in the session's trajectory recorder; the capture
        plane rides the client thread, never the tick loop."""
        if self._closed:
            raise ServerClosed("session is closed")
        self._server._submit(self, obs)
        if not self._event.wait(timeout if timeout is not None else self._server.request_timeout):
            raise TimeoutError(
                f"serve session (slot {self.slot}) timed out waiting for an action"
            )
        if self._deadline_missed:
            raise DeadlineExceeded(
                f"request exceeded its {self._server.deadline_ms:.0f}ms deadline before "
                "the tick — dropped pre-batch, session state untouched; retry"
            )
        if self._server._error is not None:
            raise ServerClosed(
                f"policy server died: {self._server._error!r}"
            ) from self._server._error
        if self._action is None:
            raise ServerClosed("policy server shut down mid-request")
        self.steps += 1
        ingest = self._server.trajectories
        if ingest is not None:
            if self._recorder is None:
                from sheeprl_tpu.serve.trajectory import SessionRecorder

                self._recorder = SessionRecorder(ingest, self.seed, self.slot)
            if reward is not None:
                self._recorder.complete(reward, next_obs=obs)
            self._recorder.begin(obs, self._action)
        return self._action

    def close(
        self,
        *,
        reward: Any = None,
        next_obs: Optional[Dict[str, np.ndarray]] = None,
        terminated: bool = False,
    ) -> None:
        """End the session. With ``reward`` (and optionally ``next_obs`` /
        ``terminated``) the final pending transition completes as the episode
        tail; without it the recorder drops the torn tail and truncates —
        evicted/shed/drained sessions never emit torn trajectories."""
        if not self._closed:
            self._closed = True
            if self._recorder is not None:
                self._recorder.finish(
                    reward=reward, next_obs=next_obs, terminated=terminated
                )
            self._server._release(self)


class PolicyServer:
    """The batching inference server. Construct, then use as a context manager
    (or call :meth:`start`/:meth:`close`); clients call :meth:`open_session`."""

    def __init__(
        self,
        policy: ServePolicy,
        *,
        slots: int = 4,
        max_batch_wait_ms: float = 2.0,
        base_seed: int = 0,
        telemetry: Any = None,
        request_timeout: float = 120.0,
        max_queue: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        degraded_wait_factor: float = DEFAULT_DEGRADED_WAIT_FACTOR,
        fault_plan: Any = None,
        trajectories: Any = None,
        explore_fraction: float = 0.0,
        explore_noise: float = 0.3,
    ) -> None:
        self.policy = policy
        self.table = SlotTable(policy, slots, base_seed=base_seed)
        self.max_batch_wait_ms = float(max_batch_wait_ms)
        self.request_timeout = float(request_timeout)
        self.max_queue = None if max_queue is None else max(int(max_queue), 0)
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self.degraded_wait_factor = max(float(degraded_wait_factor), 1.0)
        self.fault_plan = fault_plan
        self.telemetry = telemetry
        # the live flywheel's actor half: an optional TrajectoryIngest plane
        # (serve/trajectory.py) sessions record into, plus the per-slot
        # exploration split — the LOWEST round(fraction*slots) slot indices
        # are explore slots whose delivered actions get session-seeded host
        # noise; all other ("real traffic") slots stay greedy and byte-exact
        self.trajectories = trajectories
        self.explore_slots = int(round(max(min(float(explore_fraction), 1.0), 0.0) * int(slots)))
        self.explore_noise = float(explore_noise)

        self._cond = threading.Condition()
        self._admission: deque = deque()  # sessions waiting for a slot
        self._sessions: Dict[int, ServeSession] = {}  # slot -> session
        self._started_delta = 0
        self._finished_delta = 0
        self._shed_delta = 0
        self._deadline_delta = 0
        self._closing = False
        self._closed = False
        self._draining = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        # hot-reload staging: the tick loop swaps `_pending_params` in between
        # steps (never mid-tick) — clients and the reloader only ever stage
        self._pending_params: Optional[tuple] = None
        self.weight_version = 0
        self.reloads = 0
        # degraded-mode state (tick-loop-confined except the read-only flag)
        self.degraded = False
        self._saturated_ticks = 0
        self._healthy_ticks = 0
        # recent session completion times, for the retry-after estimate
        self._finish_times: deque = deque(maxlen=64)
        # preallocated [S, ...] staging buffers, zeroed rows for masked slots
        self._obs_buf = {k: spec.zeros(self.table.num_slots) for k, spec in policy.obs_spec.items()}

    # -- lifecycle -----------------------------------------------------------------

    def start(self) -> "PolicyServer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, name="sheeprl-serve", daemon=True)
        self._thread.start()
        return self

    def close(self, clean_exit: bool = True) -> None:
        with self._cond:
            # _closing may already be set by a CRASHED tick loop — the close
            # tail (join, client wakeup, telemetry summary) must still run
            # exactly once, with clean_exit=False so the stream records the
            # failure instead of never ending (watch would hang on no summary)
            if self._closed:
                return
            self._closed = True
            self._closing = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        # wake anyone still blocked on a request
        for session in list(self._sessions.values()) + list(self._admission):
            session._event.set()
        if self.telemetry is not None:
            # flush lifecycle deltas no tick observed (sessions that closed
            # after the final batch tick), then finalize the stream
            with self._cond:
                started, finished = self._started_delta, self._finished_delta
                shed, deadline_missed = self._shed_delta, self._deadline_delta
                self._started_delta = self._finished_delta = self._shed_delta = 0
                self._deadline_delta = 0
            if started or finished or shed or deadline_missed:
                self.telemetry.observe_sessions(
                    started=started,
                    finished=finished,
                    shed=shed,
                    deadline_missed=deadline_missed,
                )
            self.telemetry.close(clean_exit=clean_exit and self._error is None)

    def begin_drain(self) -> None:
        """Stop admissions (graceful shutdown, phase 1): new sessions are
        rejected with :class:`ServerClosed`, QUEUED sessions are shed (they
        never reached a slot — the grace window belongs to in-flight work),
        attached sessions keep being served. Idempotent."""
        with self._cond:
            if self._draining or self._closing:
                return
            self._draining = True
            queued = list(self._admission)
            self._admission.clear()
            for session in queued:
                session._event.set()
            self._cond.notify_all()
        # the telemetry fold happens in observe_drain (NOT via _shed_delta —
        # that would double-count when close() flushes the deltas)
        if self.telemetry is not None:
            self.telemetry.observe_drain(phase="begin", shed=len(queued))

    def drain(self, grace_s: float = 10.0, clean_exit: bool = True) -> Dict[str, int]:
        """Graceful shutdown: :meth:`begin_drain`, wait up to ``grace_s`` for
        in-flight sessions to finish, then :meth:`close` (aborting whatever is
        left — they get :class:`ServerClosed`). Returns the accounting the
        caller reports: ``{completed, aborted}`` relative to drain begin."""
        self.begin_drain()
        deadline = time.monotonic() + max(float(grace_s), 0.0)
        while time.monotonic() < deadline:
            with self._cond:
                if not self._sessions:
                    break
            time.sleep(0.02)
        with self._cond:
            aborted = len(self._sessions)
        if self.telemetry is not None:
            self.telemetry.observe_drain(
                phase="end", aborted=aborted, grace_s=float(grace_s)
            )
        self.close(clean_exit=clean_exit)
        return {"aborted": aborted}

    @property
    def draining(self) -> bool:
        return self._draining

    def __enter__(self) -> "PolicyServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(clean_exit=exc_type is None)

    # -- hot weight reload ---------------------------------------------------------

    def update_params(self, params: Any, version: int) -> None:
        """Stage a new params pytree; the tick loop swaps it in atomically
        between steps. The caller (``serve/reload.py``) has already validated
        the avals match the serving policy's — same avals ⇒ the same compiled
        ``slot_step`` program, zero recompiles; no session carry is touched."""
        with self._cond:
            if self._closing:
                raise ServerClosed("server is shutting down")
            self._pending_params = (params, int(version))
            self._cond.notify_all()

    def _apply_pending_params_locked(self) -> Optional[int]:
        """Swap staged params in (tick loop only, under the lock, between
        ticks). Returns the new version when a swap happened."""
        if self._pending_params is None:
            return None
        params, version = self._pending_params
        self._pending_params = None
        self.policy.params = params
        self.weight_version = version
        self.reloads += 1
        return version

    # -- client API ----------------------------------------------------------------

    def open_session(self, seed: Optional[int] = None) -> ServeSession:
        """Create a session; it attaches to a slot as soon as one frees up (its
        first ``step`` blocks through the admission wait). Raises
        :class:`ServerClosed` once closing/draining, :class:`ServerOverloaded`
        when the bounded admission queue is full (load shedding)."""
        with self._cond:
            if self._closing or self._error is not None:
                raise ServerClosed("server is shutting down") from self._error
            if self._draining:
                raise ServerClosed("server is draining — not admitting new sessions")
            # capacity check against the queue's CLAIM on free slots, not the
            # instantaneous table state: slots are only claimed by the tick
            # loop, so during a burst every free slot is already spoken for by
            # a queued session the loop has not admitted yet — counting them
            # is what keeps the queue actually bounded under a flood
            if (
                self.max_queue is not None
                and len(self._admission) >= self.max_queue + self.table.free_slots
            ):
                self._shed_delta += 1
                retry = self._retry_after_locked()
                raise ServerOverloaded(
                    f"admission queue is full ({len(self._admission)} waiting >= "
                    f"max_queue {self.max_queue} beyond free capacity) — retry in "
                    f"~{retry:.2f}s",
                    retry_after_s=retry,
                )
            session = ServeSession(self, seed if seed is not None else len(self._sessions))
            self._admission.append(session)
            self._started_delta += 1
            self._cond.notify_all()
            return session

    def _retry_after_locked(self) -> float:
        """Capacity estimate for the shed hint: the mean inter-finish interval
        of recent sessions, scaled by the queue a retry would land behind."""
        times = list(self._finish_times)
        waiting = len(self._admission) + 1
        if len(times) >= 2 and times[-1] > times[0]:
            per_finish = (times[-1] - times[0]) / (len(times) - 1)
            return min(max(per_finish * waiting, 0.01), 60.0)
        # no completion history yet: fall back to a coalescing-window multiple
        return min(max(self.max_batch_wait_ms / 1000.0, 0.01) * waiting, 60.0)

    @property
    def active_sessions(self) -> int:
        with self._cond:
            return len(self._sessions)

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._admission)

    # -- session plumbing ----------------------------------------------------------

    def _submit(self, session: ServeSession, obs: Dict[str, np.ndarray]) -> None:
        with self._cond:
            if self._closing or self._error is not None:
                raise ServerClosed("server is shutting down") from self._error
            session._obs = obs
            session._action = None
            session._deadline_missed = False
            session._submit_time = time.perf_counter()
            session._deadline = (
                session._submit_time + self.deadline_ms / 1000.0
                if self.deadline_ms is not None
                else None
            )
            session._event.clear()
            self._cond.notify_all()

    def _release(self, session: ServeSession) -> None:
        with self._cond:
            if session.slot is not None:
                self._sessions.pop(session.slot, None)
                self.table.evict(session.slot)
                session.slot = None
                self._finished_delta += 1
                self._finish_times.append(time.monotonic())
            elif session in self._admission:
                self._admission.remove(session)
                self._finished_delta += 1
            session._event.set()
            self._cond.notify_all()

    # -- tick loop -----------------------------------------------------------------

    def _admit_locked(self) -> Dict[int, int]:
        """Move queued sessions into free slots; returns slot -> seed for the
        attach program (caller runs it OUTSIDE the lock)."""
        attached: Dict[int, int] = {}
        while self._admission:
            slot = self.table.try_admit(self._admission[0])
            if slot is None:
                break
            session = self._admission.popleft()
            session.slot = slot
            session._attached_time = time.perf_counter()
            self._sessions[slot] = session
            attached[slot] = session.seed
            # explore-slot designation is a property of the SLOT; the noise
            # stream is a property of the SESSION (seeded by its seed, advanced
            # once per delivered action) — deterministic per session, invisible
            # to every co-batched greedy session
            session._noise_rng = (
                np.random.default_rng(session.seed)
                if slot < self.explore_slots
                else None
            )
        return attached

    def _pending_locked(self) -> List[ServeSession]:
        return [s for s in self._sessions.values() if s._obs is not None]

    def _expire_deadlines_locked(self, now: float) -> int:
        """Drop pending observations whose deadline passed BEFORE the tick:
        the request never reaches the device (the slot is masked out, carry
        bit-exact), the client gets :class:`DeadlineExceeded`."""
        if self.deadline_ms is None:
            return 0
        expired = 0
        for session in self._sessions.values():
            if (
                session._obs is not None
                and session._deadline is not None
                and now > session._deadline
            ):
                session._obs = None
                session._deadline_missed = True
                session._event.set()
                expired += 1
        self._deadline_delta += expired
        return expired

    def _update_degraded_locked(self, saturated: bool) -> Optional[bool]:
        """Degraded-mode hysteresis: sustained saturation (full table with a
        waiting queue, or shedding) widens the coalescing window by
        ``degraded_wait_factor``; sustained health narrows it back. Returns
        the new mode on a transition, None otherwise."""
        if saturated:
            self._saturated_ticks += 1
            self._healthy_ticks = 0
            if not self.degraded and self._saturated_ticks >= DEGRADED_ENTER_TICKS:
                self.degraded = True
                return True
        else:
            self._healthy_ticks += 1
            self._saturated_ticks = 0
            if self.degraded and self._healthy_ticks >= DEGRADED_EXIT_TICKS:
                self.degraded = False
                return False
        return None

    def _run(self) -> None:
        try:
            self._loop()
        except BaseException as exc:  # deliver the failure, never hang clients
            self._error = exc
            with self._cond:
                self._closing = True
                for session in list(self._sessions.values()) + list(self._admission):
                    session._event.set()
                self._cond.notify_all()

    def _emit_fault_event(self, *args: Any, **fields: Any) -> None:
        if self.telemetry is not None:
            self.telemetry.emit_event(*args, **fields)

    def _maybe_fire_fault(self, steps: int) -> None:
        """Serving fault injection: the armed plan fires once at the configured
        served step, exactly like the training loops' per-iteration hook."""
        if self.fault_plan is None:
            return
        self.fault_plan.maybe_fire(steps, self._emit_fault_event)
        from sheeprl_tpu.resilience import faults as _faults

        flood = _faults.consume_session_flood()
        if flood:
            self._spawn_flood(flood)

    def _spawn_flood(self, count: int) -> None:
        """``session_flood``: a burst of synthetic clients storming admission —
        the deterministic stand-in for a traffic spike. Shed sessions count in
        the telemetry; admitted ones run a few zero-obs steps and leave."""

        def _client(i: int) -> None:
            try:
                session = self.open_session(seed=100_000 + i)
                obs = {k: spec.zeros(1)[0] for k, spec in self.policy.obs_spec.items()}
                for _ in range(4):
                    session.step(obs)
                session.close()
            except (ServerClosed, ServerOverloaded, DeadlineExceeded, TimeoutError):
                pass

        for i in range(count):
            threading.Thread(
                target=_client, args=(i,), name=f"sheeprl-flood-{i}", daemon=True
            ).start()

    def _loop(self) -> None:
        from sheeprl_tpu.resilience import faults as _faults

        base_wait_budget = self.max_batch_wait_ms / 1000.0
        total_steps = 0
        while True:
            wait_started = time.perf_counter()
            with self._cond:
                if self._closing:
                    return
                swapped = self._apply_pending_params_locked()
                attached = self._admit_locked()
            if swapped is not None and self.telemetry is not None:
                self.telemetry.observe_reload(version=swapped)
            if attached:
                self.table.attach(attached)

            # degraded mode trades latency for occupancy: the widened window
            # lets a saturated table coalesce fuller batches instead of
            # burning ticks on partial ones
            wait_budget = base_wait_budget * (
                self.degraded_wait_factor if self.degraded else 1.0
            )

            # coalescing wait: fire when every attached session is pending, or
            # max_batch_wait_ms after the FIRST pending request arrived
            with self._cond:
                while not self._closing:
                    now = time.perf_counter()
                    self._expire_deadlines_locked(now)
                    pending = self._pending_locked()
                    if pending:
                        # remaining coalescing budget measured from the FIRST
                        # pending request — a wakeup mid-window must not re-arm
                        # the full budget (that would double the worst-case
                        # added latency)
                        oldest = min(s._submit_time for s in pending)
                        remaining = wait_budget - (now - oldest)
                        if len(pending) == len(self._sessions) or remaining <= 0:
                            break
                        # a deadline expiring mid-window must wake the loop in
                        # time to drop the request before the tick fires
                        deadlines = [
                            s._deadline - now
                            for s in pending
                            if s._deadline is not None
                        ]
                        if deadlines:
                            remaining = min(remaining, max(min(deadlines), 0.0))
                    if self._admission and self.table.free_slots:
                        break  # admit first, then come back for the batch
                    if self._pending_params is not None:
                        break  # idle reload: swap now, not at the next request
                    self._cond.wait(remaining if pending else 0.05)
                if self._closing:
                    return
                self._expire_deadlines_locked(time.perf_counter())
                pending = self._pending_locked()
                if not pending:
                    continue
                batch = [(s.slot, s) for s in pending]
                active = len(self._sessions)
                queue_depth = len(self._admission)
                started = self._started_delta
                finished = self._finished_delta
                shed = self._shed_delta
                deadline_missed = self._deadline_delta
                self._started_delta = 0
                self._finished_delta = 0
                self._shed_delta = 0
                self._deadline_delta = 0
                saturated = shed > 0 or (queue_depth > 0 and not self.table.free_slots)
                transition = self._update_degraded_locked(saturated)
            wait_seconds = time.perf_counter() - wait_started
            if transition is not None and self.telemetry is not None:
                self.telemetry.observe_degraded(transition)

            total_steps += len(batch)
            self._maybe_fire_fault(total_steps)
            slow = _faults.slow_tick_seconds()
            if slow > 0:
                # injected device-degradation: every tick pays the armed stall
                time.sleep(slow)

            # stage [S, ...] obs (zero rows for masked slots), run ONE step
            mask = np.zeros((self.table.num_slots,), np.bool_)
            for slot, session in batch:
                mask[slot] = True
                for k, buf in self._obs_buf.items():
                    buf[slot] = np.asarray(session._obs[k], dtype=buf.dtype).reshape(
                        buf.shape[1:]
                    )
            t0 = time.perf_counter()
            actions = self.table.step(self._obs_buf, mask)
            step_seconds = time.perf_counter() - t0

            now = time.perf_counter()
            latencies = []
            for slot, session in batch:
                session._obs = None
                action = np.array(actions[slot])
                if session._noise_rng is not None:
                    # additive Gaussian exploration noise, applied HOST-side
                    # after the batched device step: the compiled program (and
                    # therefore the greedy slots' actions) is byte-identical
                    # with or without explore slots co-batched. Unclipped by
                    # design — action bounds are the env adapter's contract.
                    action = (
                        action
                        + session._noise_rng.normal(0.0, self.explore_noise, action.shape)
                    ).astype(action.dtype)
                session._action = action
                # STEP latency: a queued session's first request starts its
                # clock at slot attach — time spent waiting for a slot is the
                # admission queue's number (queue_depth / slot_starvation),
                # not the step program's
                latencies.append(
                    (now - max(session._submit_time, session._attached_time)) * 1000.0
                )
                session._event.set()

            if self.telemetry is not None:
                self.telemetry.observe_tick(
                    batch=len(batch),
                    slots=self.table.num_slots,
                    active=active,
                    queue_depth=queue_depth,
                    step_seconds=step_seconds,
                    wait_seconds=wait_seconds,
                    latencies_ms=latencies,
                    started=started,
                    finished=finished,
                    shed=shed,
                    deadline_missed=deadline_missed,
                    state_bytes=self.table.state_bytes(),
                    weight_version=self.weight_version,
                    degraded=self.degraded,
                )
