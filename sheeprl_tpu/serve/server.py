"""Continuous-batching policy server over a device-resident slot table.

Concurrent sessions (each a client thread, ultimately a network frontend)
submit observations; a single tick loop coalesces whatever is pending into one
fixed-shape batched step over the slot table (``serve/slots.py``) and fans the
actions back out. Throughput is bounded by batch occupancy, not by per-session
round-trips — the continuous-batching design of LLM serving applied to
recurrent policy inference:

- **admission**: a new session waits in the queue until a slot frees up, then
  one masked ``attach`` program initializes its device carry *between* steps —
  no recompile, no effect on co-resident sessions;
- **coalescing**: a tick fires as soon as every attached session has a pending
  request, or after ``max_batch_wait_ms`` from the first pending request —
  latency is traded against occupancy with one knob;
- **masking**: sessions that did not submit this tick keep their carry
  bit-exact (the step program ``where``s them out) — a slow client never
  corrupts its own session state;
- **eviction**: closing a session frees its slot immediately; the stale carry
  is overwritten by the next admission.

The server is transport-agnostic: :meth:`PolicyServer.open_session` returns an
in-process handle (``session.step(obs) -> action``); the CLI's env driver and
the bench's open-loop generator (``serve/drivers.py``) are both plain clients.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.serve.policy import ServePolicy
from sheeprl_tpu.serve.slots import SlotTable

__all__ = ["PolicyServer", "ServeSession", "ServerClosed"]


class ServerClosed(RuntimeError):
    """The server shut down (or crashed) while a session was waiting on it."""


class ServeSession:
    """Client-side handle for one policy session. Thread-confined: one client
    thread drives ``step`` sequentially; concurrency lives ACROSS sessions."""

    def __init__(self, server: "PolicyServer", seed: int) -> None:
        self._server = server
        self.seed = int(seed)
        self.slot: Optional[int] = None
        self.steps = 0
        self._obs: Optional[Dict[str, np.ndarray]] = None
        self._action: Optional[np.ndarray] = None
        self._submit_time = 0.0
        self._attached_time = 0.0
        self._event = threading.Event()
        self._closed = False

    def step(self, obs: Dict[str, np.ndarray], timeout: Optional[float] = None) -> np.ndarray:
        """Submit one observation, block until the batched step returns this
        session's action."""
        if self._closed:
            raise ServerClosed("session is closed")
        self._server._submit(self, obs)
        if not self._event.wait(timeout if timeout is not None else self._server.request_timeout):
            raise TimeoutError(
                f"serve session (slot {self.slot}) timed out waiting for an action"
            )
        if self._server._error is not None:
            raise ServerClosed(f"policy server died: {self._server._error!r}")
        if self._action is None:
            raise ServerClosed("policy server shut down mid-request")
        self.steps += 1
        return self._action

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._server._release(self)


class PolicyServer:
    """The batching inference server. Construct, then use as a context manager
    (or call :meth:`start`/:meth:`close`); clients call :meth:`open_session`."""

    def __init__(
        self,
        policy: ServePolicy,
        *,
        slots: int = 4,
        max_batch_wait_ms: float = 2.0,
        base_seed: int = 0,
        telemetry: Any = None,
        request_timeout: float = 120.0,
    ) -> None:
        self.policy = policy
        self.table = SlotTable(policy, slots, base_seed=base_seed)
        self.max_batch_wait_ms = float(max_batch_wait_ms)
        self.request_timeout = float(request_timeout)
        self.telemetry = telemetry

        self._cond = threading.Condition()
        self._admission: deque = deque()  # sessions waiting for a slot
        self._sessions: Dict[int, ServeSession] = {}  # slot -> session
        self._started_delta = 0
        self._finished_delta = 0
        self._closing = False
        self._closed = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        # preallocated [S, ...] staging buffers, zeroed rows for masked slots
        self._obs_buf = {k: spec.zeros(self.table.num_slots) for k, spec in policy.obs_spec.items()}

    # -- lifecycle -----------------------------------------------------------------

    def start(self) -> "PolicyServer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, name="sheeprl-serve", daemon=True)
        self._thread.start()
        return self

    def close(self, clean_exit: bool = True) -> None:
        with self._cond:
            # _closing may already be set by a CRASHED tick loop — the close
            # tail (join, client wakeup, telemetry summary) must still run
            # exactly once, with clean_exit=False so the stream records the
            # failure instead of never ending (watch would hang on no summary)
            if self._closed:
                return
            self._closed = True
            self._closing = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        # wake anyone still blocked on a request
        for session in list(self._sessions.values()) + list(self._admission):
            session._event.set()
        if self.telemetry is not None:
            # flush lifecycle deltas no tick observed (sessions that closed
            # after the final batch tick), then finalize the stream
            with self._cond:
                started, finished = self._started_delta, self._finished_delta
                self._started_delta = self._finished_delta = 0
            if started or finished:
                self.telemetry.observe_sessions(started=started, finished=finished)
            self.telemetry.close(clean_exit=clean_exit and self._error is None)

    def __enter__(self) -> "PolicyServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(clean_exit=exc_type is None)

    # -- client API ----------------------------------------------------------------

    def open_session(self, seed: Optional[int] = None) -> ServeSession:
        """Create a session; it attaches to a slot as soon as one frees up (its
        first ``step`` blocks through the admission wait)."""
        with self._cond:
            if self._closing:
                raise ServerClosed("server is shutting down")
            session = ServeSession(self, seed if seed is not None else len(self._sessions))
            self._admission.append(session)
            self._started_delta += 1
            self._cond.notify_all()
            return session

    @property
    def active_sessions(self) -> int:
        with self._cond:
            return len(self._sessions)

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._admission)

    # -- session plumbing ----------------------------------------------------------

    def _submit(self, session: ServeSession, obs: Dict[str, np.ndarray]) -> None:
        with self._cond:
            if self._closing:
                raise ServerClosed("server is shutting down")
            session._obs = obs
            session._action = None
            session._submit_time = time.perf_counter()
            session._event.clear()
            self._cond.notify_all()

    def _release(self, session: ServeSession) -> None:
        with self._cond:
            if session.slot is not None:
                self._sessions.pop(session.slot, None)
                self.table.evict(session.slot)
                session.slot = None
                self._finished_delta += 1
            elif session in self._admission:
                self._admission.remove(session)
                self._finished_delta += 1
            session._event.set()
            self._cond.notify_all()

    # -- tick loop -----------------------------------------------------------------

    def _admit_locked(self) -> Dict[int, int]:
        """Move queued sessions into free slots; returns slot -> seed for the
        attach program (caller runs it OUTSIDE the lock)."""
        attached: Dict[int, int] = {}
        while self._admission:
            slot = self.table.try_admit(self._admission[0])
            if slot is None:
                break
            session = self._admission.popleft()
            session.slot = slot
            session._attached_time = time.perf_counter()
            self._sessions[slot] = session
            attached[slot] = session.seed
        return attached

    def _pending_locked(self) -> List[ServeSession]:
        return [s for s in self._sessions.values() if s._obs is not None]

    def _run(self) -> None:
        try:
            self._loop()
        except BaseException as exc:  # deliver the failure, never hang clients
            self._error = exc
            with self._cond:
                self._closing = True
                for session in list(self._sessions.values()) + list(self._admission):
                    session._event.set()
                self._cond.notify_all()

    def _loop(self) -> None:
        wait_budget = self.max_batch_wait_ms / 1000.0
        while True:
            wait_started = time.perf_counter()
            with self._cond:
                if self._closing:
                    return
                attached = self._admit_locked()
            if attached:
                self.table.attach(attached)

            # coalescing wait: fire when every attached session is pending, or
            # max_batch_wait_ms after the FIRST pending request arrived
            with self._cond:
                while not self._closing:
                    pending = self._pending_locked()
                    if pending:
                        # remaining coalescing budget measured from the FIRST
                        # pending request — a wakeup mid-window must not re-arm
                        # the full budget (that would double the worst-case
                        # added latency)
                        oldest = min(s._submit_time for s in pending)
                        remaining = wait_budget - (time.perf_counter() - oldest)
                        if len(pending) == len(self._sessions) or remaining <= 0:
                            break
                    if self._admission and self.table.free_slots:
                        break  # admit first, then come back for the batch
                    self._cond.wait(remaining if pending else 0.05)
                if self._closing:
                    return
                pending = self._pending_locked()
                if not pending:
                    continue
                batch = [(s.slot, s) for s in pending]
                active = len(self._sessions)
                queue_depth = len(self._admission)
                started = self._started_delta
                finished = self._finished_delta
                self._started_delta = 0
                self._finished_delta = 0
            wait_seconds = time.perf_counter() - wait_started

            # stage [S, ...] obs (zero rows for masked slots), run ONE step
            mask = np.zeros((self.table.num_slots,), np.bool_)
            for slot, session in batch:
                mask[slot] = True
                for k, buf in self._obs_buf.items():
                    buf[slot] = np.asarray(session._obs[k], dtype=buf.dtype).reshape(
                        buf.shape[1:]
                    )
            t0 = time.perf_counter()
            actions = self.table.step(self._obs_buf, mask)
            step_seconds = time.perf_counter() - t0

            now = time.perf_counter()
            latencies = []
            for slot, session in batch:
                session._obs = None
                session._action = np.array(actions[slot])
                # STEP latency: a queued session's first request starts its
                # clock at slot attach — time spent waiting for a slot is the
                # admission queue's number (queue_depth / slot_starvation),
                # not the step program's
                latencies.append(
                    (now - max(session._submit_time, session._attached_time)) * 1000.0
                )
                session._event.set()

            if self.telemetry is not None:
                self.telemetry.observe_tick(
                    batch=len(batch),
                    slots=self.table.num_slots,
                    active=active,
                    queue_depth=queue_depth,
                    step_seconds=step_seconds,
                    wait_seconds=wait_seconds,
                    latencies_ms=latencies,
                    started=started,
                    finished=finished,
                    state_bytes=self.table.state_bytes(),
                )
