"""Serving telemetry: the run-telemetry contract, spoken by an inference server.

A serving run writes the same ``telemetry.jsonl`` stream a training run does
(``start`` / ``window`` / ``health`` / ``summary`` events with the stream
identity triple — ``obs/jsonl.py``), so the whole PR 2–5 consumer stack works
on it unchanged: ``sheeprl.py watch`` follows it live and exits on its summary,
``sheeprl.py diagnose`` runs the detector catalog over it (including the
serving-specific detectors — occupancy_collapse, latency_regression,
slot_starvation), ``compare``/``bench-diff`` match it by fingerprint.

What differs is the payload: a serving window's unit of progress is one
*served session step* (``sps`` = served slot-steps/sec — the number ``watch``
renders), and each window carries a ``serve`` block:

- ``latency_ms``: p50/p99/mean request latency (submit → action delivered),
- ``occupancy``: mean fraction of slots doing useful work per tick,
- ``sessions``: active / started / finished / **shed** counters + sessions/sec
  and the window's ``shed_rate`` (shed / offered — the overload-protection
  number the ``shed_rate`` detector judges),
- ``queue_depth``: sessions waiting for a free slot (slot starvation signal),
- ``deadline_missed``: requests dropped pre-tick past ``serve.deadline_ms``,
- ``weights``: the hot-reload state — serving ``version``, cumulative
  ``reloads``, ``failures`` (torn/invalid candidates rejected), and the newest
  ``available`` version the reloader has seen (version > available never
  happens; available > version sustained = a stalled reload),
- ``degraded``: whether the widened coalescing window is active,
- ``ticks`` and ``state_bytes`` (the O(S) device session-state footprint),
- ``versions``: the per-weight-version split — latency percentiles, session
  lifecycle counts, deadline misses, and trajectory-plane episode returns keyed
  by the serving weight version active when each request completed (swaps land
  between ticks — ``PolicyServer._loop`` applies pending params at tick START —
  so per-tick attribution is exact). The summary carries the cumulative split;
  the ``promotion`` verdict event (emitted once a hot-reloaded version
  accumulates enough post-swap samples to judge against its predecessor) is the
  hook the canary router gates on,
- ``returns``: window aggregate of captured episode returns (mean / count),
- ``slo``: the error-budget block (``obs/slo.py``) — when objectives are
  declared, every window feeds the in-loop burn-rate evaluator and the stateful
  alert engine (``obs/alerts.py``); transitions land as ``alert`` events and
  critical firing alerts escalate through the existing ``health`` path.

Lifecycle events of the robustness plane (schema-registered in
``obs/schema.py``): ``reload`` (status=applied/rejected/stale with the version
bookkeeping), ``drain`` (status=begin/end with shed/aborted counts), and the
``fault`` events the serving fault plan emits.

Phase attribution reuses the training schema with two serving phases:
``serve_step`` (device program wall time) and ``serve_wait`` (idle, waiting for
client requests) — so ``diagnose``'s unattributed-time invariant holds on a
mostly-idle server too.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from sheeprl_tpu.obs.compile_monitor import compile_snapshot, install_compile_monitor
from sheeprl_tpu.obs.jsonl import JsonlEventSink
from sheeprl_tpu.obs.telemetry import (
    _rss_bytes,
    device_memory,
    rss_peak_bytes,
)

__all__ = ["ServingTelemetry"]

_HISTORY_CAP = 512
_LATENCY_RESERVOIR = 65536  # bounded overall-latency sample for the summary
_VERSION_RESERVOIR = 8192  # bounded per-version latency sample (promotion spread)
_RETURN_RESERVOIR = 1024  # bounded per-version episode-return sample


def _percentiles(samples) -> Optional[Dict[str, float]]:
    if not len(samples):
        return None
    arr = np.asarray(samples, dtype=np.float64)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "mean": round(float(arr.mean()), 3),
        "max": round(float(arr.max()), 3),
    }


def _spread(samples) -> float:
    """Half the p10–p90 span — the noise floor the promotion verdict and the
    version_regression detector require a latency delta to clear."""
    if len(samples) < 2:
        return 0.0
    arr = np.asarray(samples, dtype=np.float64)
    return round(float(np.percentile(arr, 90) - np.percentile(arr, 10)) / 2.0, 3)


def _slo_cfg_of(cfg: Any) -> Optional[Dict[str, Any]]:
    """``metric.telemetry.slo`` out of whatever config shape the caller holds
    (composed serve cfg, hydra DictConfig, a bare test stub) — None when the
    group is absent; never raises."""
    try:
        metric = cfg.get("metric") if hasattr(cfg, "get") else getattr(cfg, "metric", None)
        telemetry = (
            metric.get("telemetry") if hasattr(metric, "get") else getattr(metric, "telemetry", None)
        )
        slo = (
            telemetry.get("slo")
            if hasattr(telemetry, "get")
            else getattr(telemetry, "slo", None)
        )
        return dict(slo) if slo is not None else None
    except Exception:
        return None


class ServingTelemetry:
    """JSONL stream + live diagnosis for one serving run. The server calls
    :meth:`observe_tick` once per batch tick and :meth:`close` at shutdown;
    windows are emitted every ``every`` served steps."""

    def __init__(
        self,
        fabric: Any,
        cfg: Any,
        log_dir: Optional[str],
        *,
        enabled: bool = True,
        every: int = 256,
        serve_info: Optional[Dict[str, Any]] = None,
        jsonl_path: Optional[str] = None,
        diagnosis: bool = True,
        http_port: Optional[int] = None,
        http_host: str = "127.0.0.1",
        attempt: int = 0,
        rank: int = 0,
        slo: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.enabled = bool(enabled)
        self.every = max(int(every), 1)
        self.diagnosis = bool(diagnosis)
        self._device = getattr(fabric, "device", None)
        self._sink: Optional[JsonlEventSink] = None
        self._history: List[Dict[str, Any]] = []
        self._last_diagnosis_key: Any = None
        # opt-in Prometheus endpoint (metric.telemetry.http_port): the serving
        # window gauges — latency p99, occupancy, sessions/sec, queue depth —
        # scrapeable in place while the server runs; None = no socket at all
        self.metrics_endpoint = None
        if self.enabled and http_port is not None:
            from sheeprl_tpu.obs.metrics_http import build_endpoint

            self.metrics_endpoint = build_endpoint(
                {"http_port": http_port, "http_host": http_host},
                labels={"role": "serve", "algo": str(getattr(cfg.algo, "name", "?"))},
            )

        # cumulative counters
        self._steps = 0
        self._ticks = 0
        self._sessions_started = 0
        self._sessions_finished = 0
        self._sessions_shed = 0
        self._sessions_drained = 0
        self._deadline_missed = 0
        self._sessions_active = 0
        self._queue_depth = 0
        self._state_bytes: Optional[int] = None
        self._peak_hbm = 0
        # robustness-plane state (hot reload / degraded mode / drain)
        self._weight_version = 0
        self._weight_available = 0
        self._reloads = 0
        self._reload_failures = 0
        self._degraded = False
        self._draining = False
        self._drain_info: Optional[Dict[str, Any]] = None
        # per-weight-version split: cumulative + per-window accumulators keyed
        # by the version active when each request completed. Latency reservoirs
        # are bounded (a long-lived version must not grow without bound) —
        # enough samples for stable p50/p99 and the promotion verdict's spread.
        self._versions: Dict[int, Dict[str, Any]] = {}
        self._win_versions: Dict[int, Dict[str, Any]] = {}
        # episode returns by version arrive from the trajectory-ingest plane's
        # client threads — their own maps under _traj_lock, like the counters
        self._ver_returns: Dict[int, deque] = {}
        self._win_ver_returns: Dict[int, List[float]] = {}
        self._win_returns: List[float] = []
        # promotion verdicts: each applied reload anchors a pending judgment
        # (new version vs its predecessor), judged at window cadence once the
        # new version has accumulated enough post-swap samples
        self._pending_promotions: List[Dict[str, Any]] = []
        # SLO plane: objectives resolved from metric.telemetry.slo (catalog
        # defaults + config overrides + per-run slo.yaml), evaluated in-loop
        # at window cadence by the SAME machinery `sheeprl.py slo` replays
        slo_cfg = slo if slo is not None else _slo_cfg_of(cfg)
        self._promotion_min_samples = max(int((slo_cfg or {}).get("promotion_samples") or 32), 1)
        self._slo_evaluator: Any = None
        self._alert_engine: Any = None
        if self.enabled:
            from sheeprl_tpu.obs.alerts import AlertEngine
            from sheeprl_tpu.obs.slo import SloEvaluator, load_objectives

            objectives = load_objectives(slo_cfg, run_dir=log_dir)
            if objectives:
                self._slo_evaluator = SloEvaluator(objectives)
                self._alert_engine = AlertEngine(objectives)
        # trajectory-capture counters (the live flywheel's serve-side ingest:
        # captured = finished sessions that produced transitions, dropped =
        # shed by the bounded ingest queue — the explicit overflow policy)
        self._traj_captured = 0
        self._traj_ingested = 0
        self._traj_dropped = 0
        self._traj_rows = 0
        self._traj_lock = threading.Lock()
        # optional dataflow-lineage provider (ActorDataflow): snapshotted per
        # window so serve windows carry the same role="actor" dataflow block a
        # service-gang actor's do — diagnose/trace consume them unchanged
        self._dataflow: Any = None

        # window accumulators
        self._window_idx = 0
        self._win_steps = 0
        self._win_ticks = 0
        self._win_occupancy_sum = 0.0
        self._win_latencies: List[float] = []
        self._win_step_seconds = 0.0
        self._win_wait_seconds = 0.0
        self._win_queue_sum = 0
        self._win_sessions_started = 0
        self._win_sessions_finished = 0
        self._win_sessions_shed = 0
        self._win_sessions_drained = 0
        self._win_deadline_missed = 0
        self._win_traj_captured = 0
        self._win_traj_ingested = 0
        self._win_traj_dropped = 0
        self._win_traj_rows = 0
        self._all_latencies: deque = deque(maxlen=_LATENCY_RESERVOIR)

        self._start_time = time.perf_counter()
        self._anchor_time = self._start_time
        self._compile_base = {"count": 0, "seconds": 0.0}
        self._compile_last = {"count": 0, "seconds": 0.0}

        if not self.enabled:
            return
        install_compile_monitor()
        self._compile_base = compile_snapshot()
        self._compile_last = dict(self._compile_base)
        path = jsonl_path or (
            os.path.join(log_dir, "telemetry.jsonl") if log_dir else "telemetry.jsonl"
        )
        self._sink = JsonlEventSink(path, rank=int(rank), attempt=int(attempt))
        from sheeprl_tpu.obs.fingerprint import run_fingerprint

        try:
            fingerprint: Optional[Dict[str, Any]] = run_fingerprint(cfg, fabric)
        except Exception:
            fingerprint = None
        from sheeprl_tpu.obs.schema import SCHEMA_VERSION

        start_event = dict(
            schema=SCHEMA_VERSION,
            platform=getattr(self._device, "platform", None),
            device_kind=getattr(self._device, "device_kind", None),
            world_size=1,
            every=self.every,
            compile_warmup_steps=0,
            serve=dict(serve_info or {}),
            fingerprint=fingerprint,
        )
        self._append_history("start", start_event)
        self._sink.emit("start", step=None, **start_event)

    # -- per-tick hook -------------------------------------------------------------

    def observe_tick(
        self,
        *,
        batch: int,
        slots: int,
        active: int,
        queue_depth: int,
        step_seconds: float,
        wait_seconds: float,
        latencies_ms: Optional[List[float]] = None,
        started: int = 0,
        finished: int = 0,
        shed: int = 0,
        deadline_missed: int = 0,
        state_bytes: Optional[int] = None,
        weight_version: Optional[int] = None,
        degraded: Optional[bool] = None,
    ) -> None:
        """One server tick: ``batch`` sessions stepped out of ``slots`` total
        (``active`` attached), after ``wait_seconds`` of coalescing/idle wait
        and ``step_seconds`` of device program wall time. ``shed`` /
        ``deadline_missed`` are the inter-tick overload-protection deltas;
        ``weight_version``/``degraded`` snapshot the robustness-plane state."""
        if not self.enabled:
            return
        self._ticks += 1
        self._steps += int(batch)
        self._sessions_started += int(started)
        self._sessions_finished += int(finished)
        self._sessions_shed += int(shed)
        self._deadline_missed += int(deadline_missed)
        self._sessions_active = int(active)
        self._queue_depth = int(queue_depth)
        if state_bytes is not None:
            self._state_bytes = int(state_bytes)
        if weight_version is not None:
            self._weight_version = int(weight_version)
        if degraded is not None:
            self._degraded = bool(degraded)

        self._win_ticks += 1
        self._win_steps += int(batch)
        self._win_occupancy_sum += float(batch) / max(int(slots), 1)
        self._win_step_seconds += float(step_seconds)
        self._win_wait_seconds += float(wait_seconds)
        self._win_queue_sum += int(queue_depth)
        self._win_sessions_started += int(started)
        self._win_sessions_finished += int(finished)
        self._win_sessions_shed += int(shed)
        self._win_deadline_missed += int(deadline_missed)
        if latencies_ms:
            self._win_latencies.extend(float(v) for v in latencies_ms)
            self._all_latencies.extend(float(v) for v in latencies_ms)
        # per-version attribution: swaps apply between ticks, so everything
        # this tick carried belongs to the version now serving
        if batch or started or finished or shed or deadline_missed or latencies_ms:
            cum = self._version_slot(self._versions, self._weight_version)
            win = self._version_slot(self._win_versions, self._weight_version)
            for acc in (cum, win):
                acc["steps"] += int(batch)
                acc["started"] += int(started)
                acc["finished"] += int(finished)
                acc["shed"] += int(shed)
                acc["deadline_missed"] += int(deadline_missed)
            if latencies_ms:
                cum["latencies"].extend(float(v) for v in latencies_ms)
                win["latencies"].extend(float(v) for v in latencies_ms)

        if self._win_steps >= self.every:
            self._emit_window()

    def observe_sessions(
        self,
        started: int = 0,
        finished: int = 0,
        shed: int = 0,
        deadline_missed: int = 0,
    ) -> None:
        """Fold session lifecycle deltas that never rode a tick (sessions
        closing after the LAST batch tick — e.g. every session finishing its
        fixed-length episode on the same final step, or requests expiring
        between the final tick and shutdown) into the counters, so the
        summary's ``sessions_finished``/``deadline_missed`` are exact, not
        tick-sampled. The server calls this once from ``close()``."""
        if not self.enabled:
            return
        self._sessions_started += int(started)
        self._sessions_finished += int(finished)
        self._sessions_shed += int(shed)
        self._deadline_missed += int(deadline_missed)
        self._win_sessions_started += int(started)
        self._win_sessions_finished += int(finished)
        self._win_sessions_shed += int(shed)
        self._win_deadline_missed += int(deadline_missed)
        if started or finished or shed or deadline_missed:
            for acc in (
                self._version_slot(self._versions, self._weight_version),
                self._version_slot(self._win_versions, self._weight_version),
            ):
                acc["started"] += int(started)
                acc["finished"] += int(finished)
                acc["shed"] += int(shed)
                acc["deadline_missed"] += int(deadline_missed)

    @staticmethod
    def _version_slot(table: Dict[int, Dict[str, Any]], version: int) -> Dict[str, Any]:
        slot = table.get(int(version))
        if slot is None:
            slot = {
                "steps": 0,
                "started": 0,
                "finished": 0,
                "shed": 0,
                "deadline_missed": 0,
                "latencies": deque(maxlen=_VERSION_RESERVOIR),
            }
            table[int(version)] = slot
        return slot

    def observe_episode(
        self, return_: float, *, version: Optional[int] = None
    ) -> None:
        """One captured episode's return, attributed to the weight version that
        served it (the trajectory-ingest plane calls this from client threads
        at session close — hence the lock). Feeds the window's ``serve.returns``
        aggregate, the per-version split, and the promotion verdict's
        return-regression check."""
        if not self.enabled:
            return
        ver = int(version if version is not None else self._weight_version)
        with self._traj_lock:
            returns = self._ver_returns.get(ver)
            if returns is None:
                returns = self._ver_returns[ver] = deque(maxlen=_RETURN_RESERVOIR)
            returns.append(float(return_))
            self._win_ver_returns.setdefault(ver, []).append(float(return_))
            self._win_returns.append(float(return_))

    def observe_trajectories(
        self,
        *,
        captured: int = 0,
        ingested: int = 0,
        dropped: int = 0,
        rows: int = 0,
    ) -> None:
        """Trajectory-capture deltas from the ingest plane (client/worker
        threads — hence the lock): ``captured`` finished sessions offered,
        ``ingested`` shipped into the experience writer, ``dropped`` shed by
        the bounded queue, ``rows`` transitions shipped."""
        if not self.enabled:
            return
        with self._traj_lock:
            self._traj_captured += int(captured)
            self._traj_ingested += int(ingested)
            self._traj_dropped += int(dropped)
            self._traj_rows += int(rows)
            self._win_traj_captured += int(captured)
            self._win_traj_ingested += int(ingested)
            self._win_traj_dropped += int(dropped)
            self._win_traj_rows += int(rows)

    def attach_dataflow(self, provider: Any) -> None:
        """Attach a dataflow-lineage provider (``ActorDataflow``): every window
        carries its ``dataflow_snapshot()`` — the block diagnose's
        weight_staleness detector and trace's ingest→sample / publish→refresh
        flows consume, identical to a service-gang actor stream's."""
        self._dataflow = provider

    def _dataflow_block(self) -> Optional[Dict[str, Any]]:
        if self._dataflow is None:
            return None
        try:
            return self._dataflow.dataflow_snapshot()
        except Exception:
            return None

    # -- robustness-plane hooks ----------------------------------------------------

    def emit_event(self, event: str, step: Optional[int] = None, **fields: Any) -> None:
        """Raw schema-registered event passthrough (the serving fault plan's
        ``fault`` events ride this, exactly like a training loop's)."""
        if self.enabled and self._sink is not None:
            self._sink.emit(event, step=step if step is not None else self._steps, **fields)

    def observe_reload(
        self,
        *,
        version: Optional[int] = None,
        available: Optional[int] = None,
        failed: bool = False,
        reason: Optional[str] = None,
        source: Optional[str] = None,
        quiet: bool = False,
    ) -> None:
        """Hot-reload bookkeeping: an applied swap (``version``), a newer
        candidate observed (``available``), or a rejected/torn candidate
        (``failed`` + ``reason``). Applied/rejected land as ``reload`` events;
        the rolling state rides every window's ``serve.weights`` block.
        ``quiet`` counts a failure into the gauges without an event — the
        reload thread's dedupe for a persistently failing source."""
        if not self.enabled:
            return
        if available is not None:
            self._weight_available = max(self._weight_available, int(available))
        if failed:
            self._reload_failures += 1
            if quiet:
                return
            self.emit_event(
                "reload",
                status="rejected",
                version=self._weight_version,
                available=self._weight_available,
                reason=str(reason or "invalid checkpoint"),
                **({"source": source} if source else {}),
            )
            return
        if version is not None:
            baseline = self._weight_version
            self._weight_version = int(version)
            self._weight_available = max(self._weight_available, int(version))
            self._reloads += 1
            # anchor a promotion judgment: once the new version accumulates
            # enough post-swap samples, _emit_window compares it against the
            # version it replaced and emits the one-shot `promotion` verdict
            if int(version) != baseline:
                self._pending_promotions.append(
                    {"version": int(version), "baseline": int(baseline)}
                )
            self.emit_event(
                "reload",
                status="applied",
                version=int(version),
                reloads=self._reloads,
                **({"source": source} if source else {}),
            )

    def observe_degraded(self, enabled: bool) -> None:
        """Degraded-mode transition: the widened coalescing window engaged (or
        cleared) — a health event so `watch` and operators see it live."""
        if not self.enabled:
            return
        self._degraded = bool(enabled)
        self.emit_event(
            "health",
            status="degraded" if enabled else "degraded_cleared",
        )

    def observe_drain(
        self,
        *,
        phase: str,
        shed: int = 0,
        aborted: int = 0,
        grace_s: Optional[float] = None,
    ) -> None:
        """Drain lifecycle: ``begin`` (admissions stopped, queued sessions
        shed) and ``end`` (grace expired / table empty; ``aborted`` sessions
        were still in flight). The summary's ``serve.drain`` block carries the
        final accounting."""
        if not self.enabled:
            return
        if shed:
            # drain-shed sessions were already counted ``started`` at
            # admission — fold them into their own counter, NOT the overload
            # shed that feeds shed_rate's offered denominator (offered =
            # started + shed would double-count them, and a clean wind-down
            # is not the overload signal the shed_rate detector judges)
            self._sessions_drained += int(shed)
            self._win_sessions_drained += int(shed)
        if phase == "begin":
            self._draining = True
            self._drain_info = {"shed": int(shed)}
        else:
            info = self._drain_info or {}
            info.update({"aborted": int(aborted)})
            if grace_s is not None:
                info["grace_s"] = float(grace_s)
            self._drain_info = info
        self.emit_event(
            "drain",
            status=str(phase),
            shed=int(shed),
            aborted=int(aborted),
            **({"grace_s": float(grace_s)} if grace_s is not None else {}),
        )

    # -- window / summary ----------------------------------------------------------

    def _versions_block(
        self,
        table: Dict[int, Dict[str, Any]],
        returns: Dict[int, Any],
    ) -> Optional[Dict[str, Any]]:
        """The per-weight-version split (string keys — JSON object keys), only
        for versions that actually served or returned something."""
        out: Dict[str, Any] = {}
        for ver in sorted(set(table) | set(returns)):
            acc = table.get(ver)
            ver_returns = returns.get(ver)
            if not (acc and acc["steps"]) and not ver_returns:
                continue
            entry: Dict[str, Any] = {}
            if acc:
                entry.update(
                    {
                        "steps": acc["steps"],
                        "latency_ms": _percentiles(acc["latencies"]),
                        "sessions": {
                            "started": acc["started"],
                            "finished": acc["finished"],
                            "shed": acc["shed"],
                        },
                        "deadline_missed": acc["deadline_missed"],
                    }
                )
            if ver_returns:
                entry["returns"] = {
                    "mean": round(float(np.mean(ver_returns)), 4),
                    "n": len(ver_returns),
                }
            out[str(ver)] = entry
        return out or None

    def _serve_block(self, wall: float) -> Dict[str, Any]:
        ticks = max(self._win_ticks, 1)
        with self._traj_lock:
            win_ver_returns = {k: list(v) for k, v in self._win_ver_returns.items()}
            win_returns = list(self._win_returns)
        versions = self._versions_block(self._win_versions, win_ver_returns)
        # shed_rate: shed / offered, where offered = sessions that ASKED for
        # admission this window (started already excludes the shed ones)
        offered = self._win_sessions_started + self._win_sessions_shed
        return {
            **({"versions": versions} if versions else {}),
            **(
                {
                    "returns": {
                        "mean": round(float(np.mean(win_returns)), 4),
                        "n": len(win_returns),
                    }
                }
                if win_returns
                else {}
            ),
            "latency_ms": _percentiles(self._win_latencies),
            "occupancy": round(self._win_occupancy_sum / ticks, 4),
            "sessions": {
                "active": self._sessions_active,
                "started": self._win_sessions_started,
                "finished": self._win_sessions_finished,
                "shed": self._win_sessions_shed,
                "drained": self._win_sessions_drained,
                "per_sec": round(self._win_sessions_finished / wall, 3) if wall > 0 else None,
            },
            "shed_rate": round(self._win_sessions_shed / offered, 4) if offered else 0.0,
            "deadline_missed": self._win_deadline_missed,
            "queue_depth": round(self._win_queue_sum / ticks, 2),
            "weights": {
                "version": self._weight_version,
                "available": self._weight_available,
                "reloads": self._reloads,
                "failures": self._reload_failures,
            },
            "degraded": self._degraded,
            "trajectories": {
                "captured": self._win_traj_captured,
                "ingested": self._win_traj_ingested,
                "dropped": self._win_traj_dropped,
                "rows": self._win_traj_rows,
            },
            "ticks": self._win_ticks,
            "state_bytes": self._state_bytes,
        }

    def _emit_window(self, final: bool = False) -> None:
        now = time.perf_counter()
        wall = max(now - self._anchor_time, 1e-9)
        steps = self._win_steps
        if steps == 0 and final:
            return

        snap = compile_snapshot()
        window_compiles = snap["count"] - self._compile_last["count"]
        window_compile_seconds = snap["seconds"] - self._compile_last["seconds"]
        self._compile_last = dict(snap)

        hbm = device_memory(self._device) if self._device is not None else None
        if hbm and hbm.get("peak_bytes"):
            self._peak_hbm = max(self._peak_hbm, hbm["peak_bytes"])

        # tile the ROUNDED wall exactly: rounding each phase independently can
        # overshoot a sub-millisecond window by a whole 1e-4 quantum (observed:
        # sum 0.0019 vs wall 0.0018 on a fast CPU tick), which breaks the
        # sum(phases) ≈ wall invariant consumers assert — so clamp each rounded
        # phase into the rounded remainder and derive `other` from it
        wall_r = round(wall, 4)
        step_r = min(round(min(self._win_step_seconds, wall), 4), wall_r)
        wait_r = min(round(self._win_wait_seconds, 4), round(wall_r - step_r, 4))
        phases = {
            "serve_step": step_r,
            "serve_wait": max(wait_r, 0.0),
            "other": round(max(wall_r - step_r - max(wait_r, 0.0), 0.0), 4),
        }

        window_event: Dict[str, Any] = dict(
            step=self._steps,
            window=self._window_idx,
            final=bool(final),
            steps=steps,
            wall_seconds=round(wall, 4),
            sps=round(steps / wall, 3),
            serve=self._serve_block(wall),
            phases=phases,
            hbm=hbm,
            rss_bytes=_rss_bytes(),
            rss_peak_bytes=rss_peak_bytes(),
            compile={
                "count": snap["count"] - self._compile_base["count"],
                "seconds": round(snap["seconds"] - self._compile_base["seconds"], 3),
                "window_count": window_compiles,
                "window_seconds": round(window_compile_seconds, 3),
            },
        )
        dataflow = self._dataflow_block()
        if dataflow is not None:
            window_event["dataflow"] = dataflow
        # the in-loop SLO plane: feed THIS window to the burn-rate evaluator,
        # attach the budget block the window carries, and advance the alert
        # engine — identical machinery to `sheeprl.py slo`'s offline replay
        alert_transitions: List[Dict[str, Any]] = []
        slo_snapshot: Dict[str, Any] = {}
        if self._slo_evaluator is not None:
            self._slo_evaluator.observe_window(window_event)
            slo_block = self._slo_evaluator.slo_block()
            if slo_block is not None:
                window_event["slo"] = slo_block
            slo_snapshot = self._slo_evaluator.snapshot()
            alert_transitions = self._alert_engine.evaluate(slo_snapshot)
        self._append_history("window", window_event)
        if self._sink is not None:
            self._sink.emit("window", **window_event)
        # emit through the sink directly: the final window runs after close()
        # already flipped `enabled` off, and its transitions must still land
        for transition in alert_transitions:
            if self._sink is None:
                break
            self._sink.emit("alert", step=self._steps, **transition)
            # critical alerts escalate through the existing health path, so
            # every consumer already watching health sees them without growing
            # an alert-specific ear
            if transition["status"] == "firing" and transition.get("severity") == "critical":
                self._sink.emit(
                    "health",
                    step=self._steps,
                    status="alert",
                    findings=[
                        {
                            "detector": f"slo:{transition['name']}",
                            "severity": "critical",
                            "summary": (
                                f"SLO alert {transition['name']} firing "
                                f"(budget remaining {transition.get('budget_remaining')})"
                            ),
                            "suggestion": "see `sheeprl.py slo` for the budget breakdown",
                        }
                    ],
                )
        self._judge_promotions()
        if self.metrics_endpoint is not None:
            serve_block = window_event["serve"]
            lat = serve_block.get("latency_ms") or {}
            sessions = serve_block.get("sessions") or {}
            gauges = dict(
                {
                    "Perf/sps": window_event["sps"],
                    "Serve/latency_p50_ms": lat.get("p50"),
                    "Serve/latency_p99_ms": lat.get("p99"),
                    "Serve/occupancy": serve_block.get("occupancy"),
                    "Serve/sessions_active": sessions.get("active"),
                    "Serve/sessions_per_sec": sessions.get("per_sec"),
                    "Serve/sessions_shed": sessions.get("shed"),
                    "Serve/shed_rate": serve_block.get("shed_rate"),
                    "Serve/deadline_missed": serve_block.get("deadline_missed"),
                    "Serve/queue_depth": serve_block.get("queue_depth"),
                    "Serve/state_bytes": serve_block.get("state_bytes"),
                    "Serve/weight_version": (serve_block.get("weights") or {}).get("version"),
                    "Serve/reloads": (serve_block.get("weights") or {}).get("reloads"),
                    "Serve/reload_failures": (serve_block.get("weights") or {}).get("failures"),
                    "Serve/degraded": 1.0 if serve_block.get("degraded") else 0.0,
                    "Serve/trajectories_captured": (serve_block.get("trajectories") or {}).get(
                        "captured"
                    ),
                    "Serve/trajectories_dropped": (serve_block.get("trajectories") or {}).get(
                        "dropped"
                    ),
                    "Serve/draining": 1.0 if self._draining else 0.0,
                    "Compile/count": (window_event.get("compile") or {}).get("count"),
                }
            )
            # per-objective budget gauges + ALERTS-style firing gauges: the
            # single replace=True push keeps resolved alerts from lingering
            worst_remaining = None
            for name, stats in slo_snapshot.items():
                if not stats.get("samples"):
                    continue
                remaining = stats.get("budget_remaining")
                gauges[f"Slo/budget_remaining/{name}"] = remaining
                gauges[f"Slo/burn_fast/{name}"] = stats.get("burn_fast")
                if worst_remaining is None or remaining < worst_remaining:
                    worst_remaining = remaining
            if worst_remaining is not None:
                gauges["Slo/worst_budget_remaining"] = worst_remaining
            if self._alert_engine is not None:
                firing = self._alert_engine.firing()
                gauges["Alerts/firing"] = len(firing)
                for name in firing:
                    gauges[f"Alerts/firing/{name}"] = 1.0
            for ver, entry in (serve_block.get("versions") or {}).items():
                ver_lat = entry.get("latency_ms") or {}
                gauges[f"Serve/versions/v{ver}/latency_p50_ms"] = ver_lat.get("p50")
                gauges[f"Serve/versions/v{ver}/latency_p99_ms"] = ver_lat.get("p99")
                gauges[f"Serve/versions/v{ver}/steps"] = entry.get("steps")
                if entry.get("returns"):
                    gauges[f"Serve/versions/v{ver}/return_mean"] = entry["returns"].get("mean")
            self.metrics_endpoint.update(gauges)
        if self.diagnosis:
            self._run_live_diagnosis()

        self._window_idx += 1
        self._win_steps = 0
        self._win_ticks = 0
        self._win_occupancy_sum = 0.0
        self._win_latencies = []
        self._win_step_seconds = 0.0
        self._win_wait_seconds = 0.0
        self._win_queue_sum = 0
        self._win_sessions_started = 0
        self._win_sessions_finished = 0
        self._win_sessions_shed = 0
        self._win_sessions_drained = 0
        self._win_deadline_missed = 0
        self._win_versions = {}
        with self._traj_lock:
            self._win_traj_captured = 0
            self._win_traj_ingested = 0
            self._win_traj_dropped = 0
            self._win_traj_rows = 0
            self._win_ver_returns = {}
            self._win_returns = []
        self._anchor_time = now

    def _judge_promotions(self) -> None:
        """Judge pending reload promotions that accumulated enough post-swap
        samples: the new version regresses when its latency p50 sits beyond
        BOTH versions' spread above the baseline's, or its episode-return mean
        falls beyond both spreads below — one one-shot `promotion` event per
        applied version, the gate the canary router consumes."""
        if not self._pending_promotions:
            return
        still_pending: List[Dict[str, Any]] = []
        for pending in self._pending_promotions:
            version, baseline = pending["version"], pending["baseline"]
            acc = self._versions.get(version)
            samples = acc["steps"] if acc else 0
            if samples < self._promotion_min_samples:
                still_pending.append(pending)
                continue
            base = self._versions.get(baseline)
            with self._traj_lock:
                ver_returns = list(self._ver_returns.get(version) or ())
                base_returns = list(self._ver_returns.get(baseline) or ())
            fields: Dict[str, Any] = {
                "version": version,
                "baseline": baseline,
                "samples": samples,
            }
            regressions = []
            if acc and len(acc["latencies"]):
                lat = _percentiles(acc["latencies"]) or {}
                fields["latency_p50_ms"] = lat.get("p50")
                if base is not None and len(base["latencies"]):
                    base_lat = _percentiles(base["latencies"]) or {}
                    noise = _spread(acc["latencies"]) + _spread(base["latencies"])
                    fields["baseline_latency_p50_ms"] = base_lat.get("p50")
                    fields["latency_spread_ms"] = round(noise, 3)
                    if lat.get("p50", 0.0) > (base_lat.get("p50") or 0.0) + noise:
                        regressions.append("latency")
            if len(ver_returns) >= 4 and len(base_returns) >= 4:
                noise = _spread(ver_returns) + _spread(base_returns)
                mean = float(np.mean(ver_returns))
                base_mean = float(np.mean(base_returns))
                fields["return_mean"] = round(mean, 4)
                fields["baseline_return_mean"] = round(base_mean, 4)
                fields["return_spread"] = round(noise, 4)
                if mean < base_mean - noise:
                    regressions.append("return")
            if base is None or not len(base["latencies"]):
                fields["reason"] = "no baseline samples"
            elif regressions:
                fields["reason"] = "+".join(regressions) + " beyond both versions' spread"
            if self._sink is not None:
                self._sink.emit(
                    "promotion",
                    step=self._steps,
                    status="verdict",
                    verdict="regressed" if regressions else "promote",
                    **fields,
                )
        self._pending_promotions = still_pending

    def close(self, clean_exit: bool = True) -> None:
        """Flush the last partial window and the run summary; idempotent."""
        if not self.enabled:
            return
        self.enabled = False
        if self._win_steps > 0:
            self._emit_window(final=True)
        if self.metrics_endpoint is not None:
            self.metrics_endpoint.close()
            self.metrics_endpoint = None
        if self._sink is None:
            return
        wall = time.perf_counter() - self._start_time
        snap = compile_snapshot()
        hbm = device_memory(self._device) if self._device is not None else None
        peak_hbm = max(self._peak_hbm, (hbm or {}).get("peak_bytes", 0)) or None
        dataflow = self._dataflow_block()
        with self._traj_lock:
            ver_returns = {k: list(v) for k, v in self._ver_returns.items()}
        versions = self._versions_block(self._versions, ver_returns)
        slo_block = (
            self._slo_evaluator.slo_block() if self._slo_evaluator is not None else None
        )
        self._sink.emit(
            "summary",
            step=self._steps,
            **({"dataflow": dataflow} if dataflow is not None else {}),
            **({"slo": slo_block} if slo_block is not None else {}),
            clean_exit=bool(clean_exit),
            windows=self._window_idx,
            total_steps=self._steps,
            wall_seconds=round(wall, 3),
            sps=round(self._steps / wall, 3) if wall > 0 else None,
            serve={
                "latency_ms": _percentiles(self._all_latencies),
                "sessions_started": self._sessions_started,
                "sessions_finished": self._sessions_finished,
                "sessions_shed": self._sessions_shed,
                "sessions_drained": self._sessions_drained,
                "shed_rate": (
                    round(
                        self._sessions_shed
                        / (self._sessions_started + self._sessions_shed),
                        4,
                    )
                    if (self._sessions_started + self._sessions_shed)
                    else 0.0
                ),
                "deadline_missed": self._deadline_missed,
                "sessions_per_sec": round(self._sessions_finished / wall, 3)
                if wall > 0
                else None,
                "weights": {
                    "version": self._weight_version,
                    "available": self._weight_available,
                    "reloads": self._reloads,
                    "failures": self._reload_failures,
                },
                **({"versions": versions} if versions else {}),
                **({"drain": self._drain_info} if self._drain_info else {}),
                "trajectories": {
                    "captured": self._traj_captured,
                    "ingested": self._traj_ingested,
                    "dropped": self._traj_dropped,
                    "rows": self._traj_rows,
                },
                "ticks": self._ticks,
                "state_bytes": self._state_bytes,
            },
            compile={
                "count": snap["count"] - self._compile_base["count"],
                "seconds": round(snap["seconds"] - self._compile_base["seconds"], 3),
            },
            hbm_peak_bytes=peak_hbm,
            rss_peak_bytes=rss_peak_bytes(),
            health="ok",
        )
        self._sink.close()
        self._sink = None

    # -- internals -----------------------------------------------------------------

    def _append_history(self, event: str, payload: Dict[str, Any]) -> None:
        self._history.append({"event": event, "time": round(time.time(), 3), **payload})
        if len(self._history) > _HISTORY_CAP:
            del self._history[: len(self._history) - _HISTORY_CAP]

    def _run_live_diagnosis(self) -> None:
        from sheeprl_tpu.obs.diagnose import run_detectors

        findings = run_detectors(self._history)
        key = tuple(sorted((f["detector"], f["severity"]) for f in findings))
        if findings and key != self._last_diagnosis_key and self._sink is not None:
            self._sink.emit(
                "health",
                step=self._steps,
                status="diagnosis",
                findings=[
                    {k: f[k] for k in ("detector", "severity", "summary", "suggestion")}
                    for f in findings
                ],
            )
        self._last_diagnosis_key = key
