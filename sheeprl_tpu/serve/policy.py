"""The serving-side policy contract: one pure per-session step function.

A :class:`ServePolicy` is what a family's ``get_serve_policy`` extractor
(registered via ``register_serve_policy``, living next to the family's
``evaluate`` registration) distills out of a training checkpoint. It is the
*session-oriented* view of a policy:

- ``init_slot(params, key) -> carry`` builds ONE session's device-resident
  state — for recurrent/RSSM policies the O(1) per-step carry (previous
  action, GRU/RSSM latent), for feedforward policies just the PRNG key. The
  carry ALWAYS includes the session's own PRNG key, so a session's action
  stream is a pure function of (params, seed, obs sequence) — independent of
  which other sessions share its batch.
- ``step_slot(params, carry, obs) -> (action, carry')`` advances ONE session
  by one step. Pure and unbatched: the slot table vmaps it over the slot axis
  and compiles a single donated fixed-shape program
  (``serve/slots.py``), which is why admission/eviction never recompiles.

Observations arrive RAW (the dtypes the env emits — uint8 pixels, float
vectors); any normalization (pixels → [-0.5, 0.5], reshapes) happens inside
``step_slot`` so the host↔device transfer stays as small as the env's own
observation. The returned action is env-facing (argmax'd ints for discrete
spaces, floats for continuous) — what ``env.step`` accepts for one env.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Sequence, Tuple

import numpy as np

__all__ = ["ObsSpec", "ServePolicy", "resolve_serve_policy", "space_obs_spec"]


@dataclass(frozen=True)
class ObsSpec:
    """Per-session observation layout: ``shape`` WITHOUT the slot axis, and the
    dtype the env emits (uint8 pixels stage 4x cheaper than float32)."""

    shape: Tuple[int, ...]
    dtype: Any

    def zeros(self, num_slots: int) -> np.ndarray:
        return np.zeros((num_slots, *self.shape), dtype=self.dtype)


@dataclass
class ServePolicy:
    """A checkpointed policy in serving form. See the module docstring for the
    ``init_slot``/``step_slot`` contract."""

    algo: str
    params: Any
    init_slot: Callable[[Any, Any], Any]
    step_slot: Callable[[Any, Any, Dict[str, Any]], Tuple[Any, Any]]
    obs_spec: Dict[str, ObsSpec]
    action_shape: Tuple[int, ...]
    action_dtype: Any = np.float32
    # free-form description stamped into the serving telemetry start event
    meta: Dict[str, Any] = field(default_factory=dict)


def space_obs_spec(observation_space, obs_keys: Sequence[str]) -> Dict[str, ObsSpec]:
    """ObsSpec dict for the policy's encoder keys from a gym Dict space."""
    spec: Dict[str, ObsSpec] = {}
    for k in obs_keys:
        space = observation_space[k]
        spec[k] = ObsSpec(tuple(int(s) for s in space.shape), np.dtype(space.dtype))
    return spec


def resolve_serve_policy(fabric, cfg, state) -> ServePolicy:
    """Look up ``cfg.algo.name`` in the serve registry and build its policy.
    Raises with the registered set when the family has no serving extractor."""
    import importlib

    from sheeprl_tpu.utils.registry import get_serve, serve_registry

    entry = get_serve(cfg.algo.name)
    if entry is None:
        available = ", ".join(sorted(serve_registry.keys()))
        raise ValueError(
            f"no serving policy registered for algorithm {cfg.algo.name!r}; "
            f"available: {available} (add a get_serve_policy extractor next to the "
            "family's evaluate registration — see howto/serving.md)"
        )
    module = importlib.import_module(entry["module"])
    return getattr(module, entry["entrypoint"])(fabric, cfg, state)
