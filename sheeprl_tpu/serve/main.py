"""``python sheeprl.py serve checkpoint_path=<ckpt> [serve.* overrides]``.

Composition mirrors ``sheeprl-eval`` (cli.evaluation): the config is read from
the checkpoint's own ``config.yaml``, a ``serve`` block of serving knobs is
merged over it (defaults below, then dotted ``serve.*`` CLI overrides), the
checkpoint is resolved through the crash supervisor's discovery rules
(``resolve_checkpoint_path`` — a run DIR or multi-rank set resolves to its
newest manifest-valid checkpoint), and the registered family extractor builds
the :class:`~sheeprl_tpu.serve.policy.ServePolicy` the server batches.

Serving knobs (``serve.*``):

- ``slots`` — concurrent device-resident sessions (the batch dimension of the
  ONE compiled step program);
- ``max_batch_wait_ms`` — continuous-batching coalescing window;
- ``greedy`` — deterministic (mode) actions vs sampled ones;
- ``sessions`` / ``max_session_steps`` — the built-in env-session driver: N
  concurrent client threads each play a real env episode with served actions
  (the in-process session API is the transport surface; this driver is its
  operational smoke);
- ``telemetry.enabled`` / ``telemetry.every`` — the serving telemetry stream
  (``watch``/``diagnose`` compatible, see howto/serving.md);
- ``prime=true`` — compile the step/attach programs into the persistent XLA
  compile cache and exit WITHOUT serving: the ``sheeprl-compile`` story for the
  serving tier (cold-start becomes a cache hit).
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["SERVE_DEFAULTS", "build_serve_cfg", "serve_main"]

SERVE_DEFAULTS: Dict[str, Any] = {
    "slots": 4,
    "max_batch_wait_ms": 2.0,
    "greedy": True,
    "sessions": 2,
    "max_session_steps": 1000,
    "request_timeout": 120.0,
    "log_dir": None,  # default: logs/serve/<algo>_<timestamp>
    "prime": False,
    "telemetry": {"enabled": True, "every": 256},
}


def build_serve_cfg(overrides: Sequence[str]):
    """Compose the serving config: checkpoint's config.yaml + serve defaults +
    dotted CLI overrides. Returns the dotdict cfg (with ``checkpoint_path``
    resolved and ``serve`` populated)."""
    import copy

    import yaml

    from sheeprl_tpu.config import dotdict, set_by_path
    from sheeprl_tpu.resilience.discovery import resolve_checkpoint_path

    kv = dict(o.split("=", 1) for o in overrides if "=" in o)
    ckpt_arg = kv.get("checkpoint_path")
    if ckpt_arg is None:
        raise ValueError(
            "you must specify checkpoint_path=... (a checkpoint file, a run dir, "
            "or a multi-rank checkpoint dir — discovery resolves the newest valid set)"
        )
    from pathlib import Path

    ckpt_path = Path(resolve_checkpoint_path(ckpt_arg))
    cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not cfg_path.is_file():
        cfg_path = ckpt_path.parent / "config.yaml"
    if not cfg_path.is_file():
        raise ValueError(
            f"cannot serve {ckpt_path}: no config.yaml found next to the checkpoint"
        )
    with open(cfg_path) as f:
        base = yaml.safe_load(f)
    # serving is single-controller, one env worth of obs per session
    base["env"]["num_envs"] = 1
    base["env"]["capture_video"] = False
    base.setdefault("fabric", {})
    base["fabric"]["devices"] = 1
    base["checkpoint_path"] = str(ckpt_path)
    base["serve"] = copy.deepcopy(SERVE_DEFAULTS)
    cfg = dotdict(base)
    for key, raw in kv.items():
        if key == "checkpoint_path":
            continue
        try:
            value = yaml.safe_load(raw)
        except yaml.YAMLError:
            value = raw
        try:
            set_by_path(cfg, key, value, create=True)
        except (KeyError, TypeError):
            continue
    cfg.seed = int(kv.get("seed", base.get("seed", 42)))
    return cfg


def _default_log_dir(cfg) -> str:
    stamp = time.strftime("%Y-%m-%d_%H-%M-%S")
    return os.path.join("logs", "serve", f"{cfg.algo.name}_{stamp}")


def _prime(server, policy) -> Dict[str, int]:
    """AOT-compile the serving step/attach programs (landing them in the
    persistent XLA compile cache) without serving a single request."""
    import numpy as np

    from sheeprl_tpu.utils.mfu import abstractify

    table = server.table
    step, attach = table.aot_programs()
    obs = {k: spec.zeros(table.num_slots) for k, spec in policy.obs_spec.items()}
    mask = np.zeros((table.num_slots,), np.bool_)
    keys = table._slot_keys([0] * table.num_slots)
    compiled = 0
    for fn, args in (
        (step, (policy.params, table.states, obs, mask)),
        (attach, (policy.params, table.states, keys, mask)),
    ):
        fn.lower(*abstractify(args)).compile()
        compiled += 1
    return {"programs": compiled, "slots": table.num_slots}


def serve_main(args: Optional[Sequence[str]] = None) -> int:
    """The ``serve`` verb implementation (called by ``sheeprl_tpu.cli.serve``)."""
    import jax

    import sheeprl_tpu  # noqa: F401 — populate the serve registry

    from sheeprl_tpu.parallel.fabric import Fabric
    from sheeprl_tpu.serve.drivers import run_env_sessions
    from sheeprl_tpu.serve.policy import resolve_serve_policy
    from sheeprl_tpu.serve.server import PolicyServer
    from sheeprl_tpu.serve.telemetry import ServingTelemetry
    from sheeprl_tpu.utils.checkpoint import load_checkpoint
    from sheeprl_tpu.utils.compile_cache import enable_compile_cache

    overrides = list(args if args is not None else sys.argv[1:])
    cfg = build_serve_cfg(overrides)
    serve_cfg = cfg.serve

    # the persistent compile cache is the serving cold-start story: a primed
    # (serve.prime=true) or previously-served policy compiles as a cache hit
    enable_compile_cache()

    fabric = Fabric(
        devices=1,
        accelerator=cfg.fabric.get("accelerator", "auto"),
        precision=cfg.fabric.get("precision", "32-true"),
        checkpoint_backend=str((cfg.get("checkpoint") or {}).get("backend", "pickle")),
    )
    # pin the platform BEFORE loading (same rationale as eval_algorithm)
    fabric._setup()
    state = load_checkpoint(cfg.checkpoint_path)
    policy = resolve_serve_policy(fabric, cfg, state)

    log_dir = serve_cfg.get("log_dir") or _default_log_dir(cfg)
    os.makedirs(log_dir, exist_ok=True)
    tcfg = serve_cfg.get("telemetry") or {}
    # the live metrics endpoint rides the training config surface
    # (metric.telemetry.http_port — overridable on the serve command line), so
    # one knob makes trainers AND servers scrapeable the same way
    metric_tcfg = ((cfg.get("metric") or {}).get("telemetry")) or {}
    telemetry = ServingTelemetry(
        fabric,
        cfg,
        log_dir,
        enabled=bool(tcfg.get("enabled", True)),
        every=int(tcfg.get("every", 256)),
        http_port=metric_tcfg.get("http_port"),
        http_host=str(metric_tcfg.get("http_host") or "127.0.0.1"),
        serve_info={
            "slots": int(serve_cfg.slots),
            "max_batch_wait_ms": float(serve_cfg.max_batch_wait_ms),
            "greedy": bool(serve_cfg.greedy),
            "checkpoint_path": str(cfg.checkpoint_path),
            **policy.meta,
        },
    )

    server = PolicyServer(
        policy,
        slots=int(serve_cfg.slots),
        max_batch_wait_ms=float(serve_cfg.max_batch_wait_ms),
        base_seed=int(cfg.seed),
        telemetry=telemetry,
        request_timeout=float(serve_cfg.request_timeout),
    )

    if bool(serve_cfg.get("prime")):
        t0 = time.perf_counter()
        stats = _prime(server, policy)
        telemetry.close(clean_exit=True)
        cache_dir = jax.config.jax_compilation_cache_dir
        print(
            f"[sheeprl-serve] primed {stats['programs']} serving program(s) for "
            f"{cfg.algo.name} ({stats['slots']} slots) in {time.perf_counter() - t0:.1f}s"
            + (
                f" — persistent cache at {cache_dir}"
                if cache_dir
                else " — WARNING: persistent compile cache is DISABLED (SHEEPRL_JAX_CACHE=0?)"
            )
        )
        return 0

    sessions = int(serve_cfg.sessions)
    if sessions < 1:
        telemetry.close(clean_exit=True)
        print(
            "[sheeprl-serve] serve.sessions=0: nothing to drive. The in-process "
            "session API (PolicyServer.open_session) is the transport surface; "
            "set serve.sessions=N to run N concurrent env sessions to completion.",
            file=sys.stderr,
        )
        return 2

    print(
        f"[sheeprl-serve] serving {cfg.algo.name} from {cfg.checkpoint_path} — "
        f"{serve_cfg.slots} slots, {sessions} env session(s), telemetry at {log_dir}"
    )
    results: List[Dict[str, Any]]
    with server:
        results = run_env_sessions(
            server,
            cfg,
            sessions=sessions,
            max_session_steps=int(serve_cfg.max_session_steps),
            log_dir=log_dir,
        )
    failed = [r for r in results if r.get("error")]
    for r in results:
        print(
            f"[sheeprl-serve] session seed={r.get('seed')}: {r.get('steps', 0)} steps, "
            f"reward {r.get('reward', 0.0):.2f}"
            + (f" — ERROR {r['error']}" if r.get("error") else "")
        )
    return 1 if failed else 0
