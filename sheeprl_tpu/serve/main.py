"""``python sheeprl.py serve checkpoint_path=<ckpt> [serve.* overrides]``.

Composition mirrors ``sheeprl-eval`` (cli.evaluation): the config is read from
the checkpoint's own ``config.yaml``, a ``serve`` block of serving knobs is
merged over it (defaults below, then dotted ``serve.*`` CLI overrides), the
checkpoint is resolved through the crash supervisor's discovery rules
(``resolve_checkpoint_path`` — a run DIR or multi-rank set resolves to its
newest manifest-valid checkpoint), and the registered family extractor builds
the :class:`~sheeprl_tpu.serve.policy.ServePolicy` the server batches.

Serving knobs (``serve.*``):

- ``slots`` — concurrent device-resident sessions (the batch dimension of the
  ONE compiled step program);
- ``max_batch_wait_ms`` — continuous-batching coalescing window;
- ``greedy`` — deterministic (mode) actions vs sampled ones;
- ``sessions`` / ``max_session_steps`` — the built-in env-session driver: N
  concurrent client threads each play a real env episode with served actions
  (the in-process session API is the transport surface; this driver is its
  operational smoke);
- ``max_queue`` — bounded admission queue: sessions arriving past it are shed
  with ``ServerOverloaded`` (+ retry-after hint) instead of queueing forever
  (null = unbounded, the pre-robustness behavior);
- ``deadline_ms`` — per-request deadline: a pending observation older than
  this is dropped BEFORE the tick and the client gets ``DeadlineExceeded``;
- ``degraded_wait_factor`` — how much the coalescing window widens under
  sustained saturation (degraded mode);
- ``drain_grace_s`` — SIGTERM drain: stop admissions, let in-flight sessions
  finish for this long, then close with a clean summary and exit 75;
- ``reload.{enabled,poll_s,watch_dir}`` — hot weight reload: follow the
  watched directory's newest valid checkpoint (``serve/reload.py``) and swap
  params in atomically between ticks, zero recompiles;
- ``supervisor.{enabled,max_restarts,backoff,...}`` — bounded-restart
  supervision of the serve loop itself (the training supervisor's
  ``run_restart_policy``), with session-loss accounting per restart;
- ``telemetry.enabled`` / ``telemetry.every`` — the serving telemetry stream
  (``watch``/``diagnose`` compatible, see howto/serving.md); with
  ``metric.telemetry.http_port`` set, ``/metrics`` (Prometheus) and
  ``/healthz`` (readiness: 200 serving / 503 draining-or-loading) ride it;
- ``prime=true`` — compile the step/attach programs into the persistent XLA
  compile cache and exit WITHOUT serving: the ``sheeprl-compile`` story for the
  serving tier (cold-start becomes a cache hit).

Exit codes: ``0`` every session completed, ``1`` a session failed or the
server crashed (restart budget exhausted when supervised), ``2`` nothing to
drive, ``75`` (EX_TEMPFAIL, the resilience plane's preempted code) SIGTERM →
drained cleanly — external supervisors reschedule, exactly as for training.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Optional, Sequence

__all__ = ["SERVE_DEFAULTS", "build_serve_cfg", "serve_main"]

SERVE_DEFAULTS: Dict[str, Any] = {
    "slots": 4,
    "max_batch_wait_ms": 2.0,
    "greedy": True,
    "sessions": 2,
    "max_session_steps": 1000,
    "request_timeout": 120.0,
    "log_dir": None,  # default: logs/serve/<algo>_<timestamp>
    "prime": False,
    # robustness plane (howto/serving.md, "Operating a server")
    "max_queue": None,  # null = unbounded admission (no shedding)
    "deadline_ms": None,  # null = no per-request deadline
    # per-slot exploration split (the live flywheel, howto/live.md): the lowest
    # round(fraction*slots) slot indices get session-seeded Gaussian action
    # noise; all other slots serve greedy, byte-identical actions
    "explore": {"fraction": 0.0, "noise": 0.3},
    "degraded_wait_factor": 4.0,
    "drain_grace_s": 10.0,
    "reload": {"enabled": False, "poll_s": 2.0, "watch_dir": None},
    "supervisor": {
        "enabled": False,
        "max_restarts": 3,
        "backoff": 1.0,
        "backoff_cap": 60.0,
    },
    "telemetry": {"enabled": True, "every": 256},
}


def build_serve_cfg(overrides: Sequence[str]):
    """Compose the serving config: checkpoint's config.yaml + serve defaults +
    dotted CLI overrides. Returns the dotdict cfg (with ``checkpoint_path``
    resolved and ``serve`` populated)."""
    import copy

    import yaml

    from sheeprl_tpu.config import dotdict, set_by_path
    from sheeprl_tpu.resilience.discovery import resolve_checkpoint_path

    kv = dict(o.split("=", 1) for o in overrides if "=" in o)
    ckpt_arg = kv.get("checkpoint_path")
    if ckpt_arg is None:
        raise ValueError(
            "you must specify checkpoint_path=... (a checkpoint file, a run dir, "
            "or a multi-rank checkpoint dir — discovery resolves the newest valid set)"
        )
    from pathlib import Path

    ckpt_path = Path(resolve_checkpoint_path(ckpt_arg))
    cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not cfg_path.is_file():
        cfg_path = ckpt_path.parent / "config.yaml"
    if not cfg_path.is_file():
        raise ValueError(
            f"cannot serve {ckpt_path}: no config.yaml found next to the checkpoint"
        )
    with open(cfg_path) as f:
        base = yaml.safe_load(f)
    # serving is single-controller, one env worth of obs per session
    base["env"]["num_envs"] = 1
    base["env"]["capture_video"] = False
    base.setdefault("fabric", {})
    base["fabric"]["devices"] = 1
    base["checkpoint_path"] = str(ckpt_path)
    base["serve"] = copy.deepcopy(SERVE_DEFAULTS)
    cfg = dotdict(base)
    for key, raw in kv.items():
        if key == "checkpoint_path":
            continue
        try:
            value = yaml.safe_load(raw)
        except yaml.YAMLError:
            value = raw
        try:
            set_by_path(cfg, key, value, create=True)
        except (KeyError, TypeError):
            continue
    cfg.seed = int(kv.get("seed", base.get("seed", 42)))
    # hot reload follows the checkpoint SOURCE the operator pointed at: a run
    # dir keeps producing newer checkpoints under it, an exact file's parent
    # is the closest thing to one
    if cfg.serve.reload.get("watch_dir") is None:
        cfg.serve.reload.watch_dir = (
            str(ckpt_arg) if os.path.isdir(str(ckpt_arg)) else str(ckpt_path.parent)
        )
    return cfg


def _default_log_dir(cfg) -> str:
    stamp = time.strftime("%Y-%m-%d_%H-%M-%S")
    return os.path.join("logs", "serve", f"{cfg.algo.name}_{stamp}")


def _prime(server, policy) -> Dict[str, int]:
    """AOT-compile the serving step/attach programs (landing them in the
    persistent XLA compile cache) without serving a single request."""
    import numpy as np

    from sheeprl_tpu.utils.mfu import abstractify

    table = server.table
    step, attach = table.aot_programs()
    obs = {k: spec.zeros(table.num_slots) for k, spec in policy.obs_spec.items()}
    mask = np.zeros((table.num_slots,), np.bool_)
    keys = table._slot_keys([0] * table.num_slots)
    compiled = 0
    for fn, args in (
        (step, (policy.params, table.states, obs, mask)),
        (attach, (policy.params, table.states, keys, mask)),
    ):
        fn.lower(*abstractify(args)).compile()
        compiled += 1
    return {"programs": compiled, "slots": table.num_slots}


class _ServeAttempt:
    """One serving attempt: server + telemetry + reloader + the drain watcher.
    The supervisor path runs several of these against one telemetry stream
    (per-attempt identity), the plain path exactly one."""

    def __init__(self, cfg: Any, fabric: Any, log_dir: str, attempt: int = 0) -> None:
        from sheeprl_tpu.resilience.faults import build_fault_plan
        from sheeprl_tpu.serve.policy import resolve_serve_policy
        from sheeprl_tpu.serve.server import PolicyServer
        from sheeprl_tpu.serve.telemetry import ServingTelemetry
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        self.cfg = cfg
        self.fabric = fabric
        self.log_dir = log_dir
        serve_cfg = cfg.serve

        state = load_checkpoint(cfg.checkpoint_path)
        self.policy = resolve_serve_policy(fabric, cfg, state)

        tcfg = serve_cfg.get("telemetry") or {}
        metric_tcfg = ((cfg.get("metric") or {}).get("telemetry")) or {}
        self.telemetry = ServingTelemetry(
            fabric,
            cfg,
            log_dir,
            enabled=bool(tcfg.get("enabled", True)),
            every=int(tcfg.get("every", 256)),
            http_port=metric_tcfg.get("http_port"),
            http_host=str(metric_tcfg.get("http_host") or "127.0.0.1"),
            attempt=attempt,
            serve_info={
                "slots": int(serve_cfg.slots),
                "max_batch_wait_ms": float(serve_cfg.max_batch_wait_ms),
                "greedy": bool(serve_cfg.greedy),
                "checkpoint_path": str(cfg.checkpoint_path),
                **self.policy.meta,
            },
        )
        self.server = PolicyServer(
            self.policy,
            slots=int(serve_cfg.slots),
            max_batch_wait_ms=float(serve_cfg.max_batch_wait_ms),
            base_seed=int(cfg.seed),
            telemetry=self.telemetry,
            request_timeout=float(serve_cfg.request_timeout),
            max_queue=serve_cfg.get("max_queue"),
            deadline_ms=serve_cfg.get("deadline_ms"),
            degraded_wait_factor=float(serve_cfg.get("degraded_wait_factor") or 4.0),
            fault_plan=build_fault_plan(cfg.get("resilience")),
            explore_fraction=float((serve_cfg.get("explore") or {}).get("fraction") or 0.0),
            explore_noise=float((serve_cfg.get("explore") or {}).get("noise") or 0.3),
        )
        self.reloader = None
        reload_cfg = serve_cfg.get("reload") or {}
        if bool(reload_cfg.get("enabled")):
            from sheeprl_tpu.serve.reload import CheckpointReloadSource, WeightReloader

            source = CheckpointReloadSource(
                str(reload_cfg.get("watch_dir") or os.path.dirname(cfg.checkpoint_path)),
                fabric,
                cfg,
                current_path=str(cfg.checkpoint_path),
            )
            # no explicit device: staged params stay uncommitted like the boot
            # params, so a swap never changes the step/attach jit signature
            self.reloader = WeightReloader(
                self.server,
                source,
                telemetry=self.telemetry,
                poll_s=float(reload_cfg.get("poll_s") or 2.0),
            )
        self.drained = False
        self._stop_watch = threading.Event()
        self._watcher: Optional[threading.Thread] = None

    # -- drain / health watcher ----------------------------------------------------

    def _set_health(self, ready: bool, status: str) -> None:
        endpoint = getattr(self.telemetry, "metrics_endpoint", None)
        if endpoint is not None:
            endpoint.set_health(
                {
                    "ready": ready,
                    "status": status,
                    "draining": self.server.draining,
                    "degraded": self.server.degraded,
                    "weight_version": self.server.weight_version,
                    "sessions_active": self.server.active_sessions,
                    "queue_depth": self.server.queue_depth,
                }
            )

    def _watch(self) -> None:
        from sheeprl_tpu.resilience import signals

        grace = float(self.cfg.serve.get("drain_grace_s") or 10.0)
        while not self._stop_watch.wait(0.2):
            if signals.preemption_requested() and not self.drained:
                # cooperative SIGTERM → graceful drain: stop admissions, let
                # in-flight sessions finish inside the grace window, close
                # with a CLEAN summary (this is a wind-down, not a crash)
                self.drained = True
                self._set_health(False, "draining")
                print(
                    f"[sheeprl-serve] preemption requested: draining (grace "
                    f"{grace:.0f}s) — admissions stopped, in-flight sessions finishing",
                    file=sys.stderr,
                    flush=True,
                )
                self.server.drain(grace, clean_exit=True)
                return
            self._set_health(True, "ok")

    # -- lifecycle -----------------------------------------------------------------

    def run(self) -> Dict[str, Any]:
        """Serve the configured env sessions to completion (or drain). Returns
        ``{results, preempted, error, sessions_lost}``."""
        from sheeprl_tpu.resilience import signals
        from sheeprl_tpu.serve.drivers import run_env_sessions

        serve_cfg = self.cfg.serve
        sessions = int(serve_cfg.sessions)
        self.server.start()
        if self.reloader is not None:
            self.reloader.start()
        self._set_health(True, "ok")
        self._watcher = threading.Thread(
            target=self._watch, name="sheeprl-serve-watch", daemon=True
        )
        self._watcher.start()
        try:
            results = run_env_sessions(
                self.server,
                self.cfg,
                sessions=sessions,
                max_session_steps=int(serve_cfg.max_session_steps),
                log_dir=self.log_dir,
            )
        finally:
            if self.reloader is not None:
                self.reloader.stop()
            self._stop_watch.set()
            preempted = signals.preemption_requested()
            if preempted and self._watcher is not None:
                # let the watcher finish the drain it owns (grace-bounded)
                self._watcher.join(
                    timeout=float(serve_cfg.get("drain_grace_s") or 10.0) + 30.0
                )
            self._set_health(False, "stopped")
            self.server.close(clean_exit=self.server._error is None)
        lost = [r for r in results if r.get("error")]
        return {
            "results": results,
            "preempted": preempted,
            "error": self.server._error,
            # a drained session ended by the server, not by its episode: those
            # are wind-down casualties, not lost state; LOST sessions are the
            # crash path's — the supervisor's restart event carries the count
            "sessions_lost": len(lost),
        }


def serve_main(args: Optional[Sequence[str]] = None) -> int:
    """The ``serve`` verb implementation (called by ``sheeprl_tpu.cli.serve``)."""
    import jax

    import sheeprl_tpu  # noqa: F401 — populate the serve registry

    from sheeprl_tpu.parallel.fabric import Fabric
    from sheeprl_tpu.resilience import signals
    from sheeprl_tpu.resilience.restart_policy import RestartPolicy, run_restart_policy
    from sheeprl_tpu.serve.policy import resolve_serve_policy
    from sheeprl_tpu.serve.server import PolicyServer
    from sheeprl_tpu.serve.telemetry import ServingTelemetry
    from sheeprl_tpu.utils.checkpoint import load_checkpoint
    from sheeprl_tpu.utils.compile_cache import enable_compile_cache

    overrides = list(args if args is not None else sys.argv[1:])
    cfg = build_serve_cfg(overrides)
    serve_cfg = cfg.serve

    # the persistent compile cache is the serving cold-start story: a primed
    # (serve.prime=true) or previously-served policy compiles as a cache hit
    enable_compile_cache()

    fabric = Fabric(
        devices=1,
        accelerator=cfg.fabric.get("accelerator", "auto"),
        precision=cfg.fabric.get("precision", "32-true"),
        checkpoint_backend=str((cfg.get("checkpoint") or {}).get("backend", "pickle")),
    )
    # pin the platform BEFORE loading (same rationale as eval_algorithm)
    fabric._setup()

    if bool(serve_cfg.get("prime")):
        state = load_checkpoint(cfg.checkpoint_path)
        policy = resolve_serve_policy(fabric, cfg, state)
        server = PolicyServer(
            policy,
            slots=int(serve_cfg.slots),
            max_batch_wait_ms=float(serve_cfg.max_batch_wait_ms),
            base_seed=int(cfg.seed),
        )
        t0 = time.perf_counter()
        stats = _prime(server, policy)
        cache_dir = jax.config.jax_compilation_cache_dir
        print(
            f"[sheeprl-serve] primed {stats['programs']} serving program(s) for "
            f"{cfg.algo.name} ({stats['slots']} slots) in {time.perf_counter() - t0:.1f}s"
            + (
                f" — persistent cache at {cache_dir}"
                if cache_dir
                else " — WARNING: persistent compile cache is DISABLED (SHEEPRL_JAX_CACHE=0?)"
            )
        )
        return 0

    sessions = int(serve_cfg.sessions)
    if sessions < 1:
        print(
            "[sheeprl-serve] serve.sessions=0: nothing to drive. The in-process "
            "session API (PolicyServer.open_session) is the transport surface; "
            "set serve.sessions=N to run N concurrent env sessions to completion.",
            file=sys.stderr,
        )
        return 2

    log_dir = serve_cfg.get("log_dir") or _default_log_dir(cfg)
    os.makedirs(log_dir, exist_ok=True)

    # cooperative SIGTERM handling — lifecycle parity with training: the
    # handler records, the drain watcher acts (main-thread only; a serve
    # driven from a worker thread still drains via request_preemption)
    handler_installed = signals.install_preemption_handler()

    reload_cfg = serve_cfg.get("reload") or {}
    print(
        f"[sheeprl-serve] serving {cfg.algo.name} from {cfg.checkpoint_path} — "
        f"{serve_cfg.slots} slots, {sessions} env session(s), telemetry at {log_dir}"
        + (
            f", hot reload following {reload_cfg.get('watch_dir')}"
            if bool(reload_cfg.get("enabled"))
            else ""
        )
    )

    sup_cfg = serve_cfg.get("supervisor") or {}
    try:
        if not bool(sup_cfg.get("enabled")):
            info = _ServeAttempt(cfg, fabric, log_dir, attempt=0).run()
            return _verdict(info)

        # bounded-restart supervision of the serve loop itself: the training
        # supervisor's policy loop, with session-loss accounting per restart
        policy_obj = RestartPolicy.from_cfg(sup_cfg)
        # a preempted (SIGTERM-drained) serve EXITS 75 for the external
        # supervisor — restarting it in-process would undo the drain
        policy_obj.restart_on_preempt = False
        from sheeprl_tpu.obs.jsonl import JsonlEventSink

        sink = JsonlEventSink(os.path.join(log_dir, "telemetry.jsonl"))
        state: Dict[str, Any] = {"info": None, "lost_total": 0}

        def emit(event: str, **fields: Any) -> None:
            fields.setdefault("attempt", policy_obj.attempt)
            sink.emit(event, **fields)

        def run_attempt(attempt: int):
            try:
                info = _ServeAttempt(cfg, fabric, log_dir, attempt=attempt).run()
            except Exception as err:  # SystemExit/KeyboardInterrupt propagate
                # a boot-time crash (checkpoint read, telemetry port bind)
                # never reached the tick loop: no sessions existed, but the
                # restart budget must govern it like any crashed attempt
                info = {
                    "results": [],
                    "preempted": False,
                    "error": err,
                    "sessions_lost": 0,
                }
            state["info"] = info
            if info["preempted"]:
                return "preempt", info
            if info["error"] is not None:
                state["lost_total"] += int(info["sessions_lost"])
                return "crash", info
            return "completed", info

        def restart_fields(attempt, outcome, info):
            return {
                "error": repr(info.get("error"))[:500] if info.get("error") else None,
                "sessions_lost": int(info.get("sessions_lost") or 0),
                "sessions_lost_total": int(state["lost_total"]),
            }

        def giveup_fields(info):
            return {
                "error": repr(info.get("error")) if info.get("error") else None,
                "sessions_lost_total": int(state["lost_total"]),
            }

        def on_giveup(outcome, info):
            if info.get("error") is not None:
                raise info["error"]
            return "preempted"

        try:
            run_restart_policy(
                policy_obj,
                run_attempt,
                emit,
                restart_fields=restart_fields,
                giveup_fields=giveup_fields,
                on_giveup=on_giveup,
            )
        finally:
            sink.close()
        return _verdict(state["info"])
    finally:
        if handler_installed:
            signals.uninstall_preemption_handler()


def _verdict(info: Optional[Dict[str, Any]]) -> int:
    """Map one attempt's outcome onto the serve exit-code taxonomy."""
    from sheeprl_tpu.resilience.signals import PREEMPTED_EXIT_CODE

    if info is None:
        return 1
    for r in info["results"]:
        print(
            f"[sheeprl-serve] session seed={r.get('seed')}: {r.get('steps', 0)} steps, "
            f"reward {r.get('reward', 0.0):.2f}"
            + (f" — ERROR {r['error']}" if r.get("error") else "")
        )
    if info["preempted"]:
        print(
            "[sheeprl-serve] drained after preemption request — clean exit "
            f"(code {PREEMPTED_EXIT_CODE})"
        )
        return PREEMPTED_EXIT_CODE
    if info["error"] is not None:
        print(f"[sheeprl-serve] server crashed: {info['error']!r}", file=sys.stderr)
        return 1
    return 1 if any(r.get("error") for r in info["results"]) else 0
