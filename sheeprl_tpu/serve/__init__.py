"""sheeprl_tpu.serve — the policy serving tier (ROADMAP item 3).

Training produces checkpoints; this package serves them under load:
``python sheeprl.py serve checkpoint_path=<ckpt>`` loads any registered agent
checkpoint, compiles ONE donated fixed-shape step program per policy, and
serves concurrent sessions via continuous batching over a device-resident
slot table (O(1) recurrent/RSSM session state per step, updated in place).

Layout (shape parity with ``obs/`` and ``resilience/``):

- ``policy.py``  — the :class:`ServePolicy` contract + per-family registry
- ``slots.py``   — the device slot table and its donated step/attach programs
- ``server.py``  — the continuous-batching server + in-process session API,
  with the robustness plane: overload shedding, deadlines, degraded mode,
  graceful drain, atomic hot weight swap
- ``reload.py``  — hot weight reload sources + the reload thread
- ``drivers.py`` — env-session and open-loop load clients
- ``telemetry.py`` — the serving telemetry stream (watch/diagnose-compatible)
- ``main.py``    — the CLI verb implementation + compile-cache priming

See ``howto/serving.md``.
"""

from __future__ import annotations

from sheeprl_tpu.serve.policy import ObsSpec, ServePolicy, resolve_serve_policy, space_obs_spec
from sheeprl_tpu.serve.reload import (
    CheckpointReloadSource,
    SubscriberReloadSource,
    WeightReloader,
)
from sheeprl_tpu.serve.server import (
    DeadlineExceeded,
    PolicyServer,
    ServeSession,
    ServerClosed,
    ServerOverloaded,
)
from sheeprl_tpu.serve.slots import SlotTable
from sheeprl_tpu.serve.telemetry import ServingTelemetry

__all__ = [
    "CheckpointReloadSource",
    "DeadlineExceeded",
    "ObsSpec",
    "PolicyServer",
    "ServePolicy",
    "ServeSession",
    "ServerClosed",
    "ServerOverloaded",
    "ServingTelemetry",
    "SlotTable",
    "SubscriberReloadSource",
    "WeightReloader",
    "resolve_serve_policy",
    "space_obs_spec",
]
