"""Model building blocks, Flax-native.

Re-provides the reference block library (sheeprl/models/models.py: MLP:16, CNN:122,
DeCNN:205, NatureCNN:288, LayerNormGRUCell:331, MultiEncoder:413, MultiDecoder:478,
LayerNormChannelLast:507) as Flax linen modules designed for the TPU:

- images flow **NHWC** internally (XLA's preferred TPU layout; the host side keeps the
  reference's channel-first arrays and encoders transpose on entry);
- every block takes a ``dtype`` so bf16-mixed runs keep params in fp32 and compute in
  bf16 on the MXU;
- the GRU cell is a single fused step usable under ``lax.scan`` (the reference calls it
  per-timestep from a Python loop, sheeprl/algos/dreamer_v3/dreamer_v3.py:86-97).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.ops.conv import FastConv2x

ModuleType = Optional[str]
ArgType = Union[Tuple[Any, ...], Dict[str, Any], None]

_ACTIVATIONS: Dict[str, Callable] = {
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leaky_relu": jax.nn.leaky_relu,
    "leakyrelu": jax.nn.leaky_relu,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus,
    "identity": lambda x: x,
    "none": lambda x: x,
}


def resolve_activation(act: Union[None, str, Callable]) -> Callable:
    """Accept jax-style names ("tanh"), torch-style names ("torch.nn.Tanh") and plain
    callables, so reference config trees run unmodified."""
    if act is None:
        return lambda x: x
    if callable(act):
        return act
    name = str(act).split(".")[-1].lower()
    if name in _ACTIVATIONS:
        return _ACTIVATIONS[name]
    raise ValueError(f"unknown activation {act!r}")


class MLP(nn.Module):
    """Per-layer [Dense → dropout? → norm? → act?] stack with optional flatten of the
    input (reference models.py:16-119; layer ordering per its miniblock contract:
    dropout before the normalization, both before the activation)."""

    hidden_sizes: Sequence[int] = ()
    output_dim: Optional[int] = None
    activation: Union[None, str, Callable] = "relu"
    layer_norm: bool = False
    dropout: float = 0.0
    flatten_dim: Optional[int] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        act = resolve_activation(self.activation)
        if self.flatten_dim is not None:
            x = jnp.reshape(x, (*x.shape[: self.flatten_dim], -1))
        x = x.astype(self.dtype)
        for size in self.hidden_sizes:
            x = nn.Dense(size, dtype=self.dtype)(x)
            if self.dropout > 0.0:
                x = nn.Dropout(rate=self.dropout, deterministic=deterministic)(x)
            if self.layer_norm:
                x = nn.LayerNorm(dtype=self.dtype, epsilon=1e-5)(x)
            x = act(x)
        if self.output_dim is not None:
            x = nn.Dense(self.output_dim, dtype=self.dtype)(x)
        return x


class CNN(nn.Module):
    """Conv stack over NHWC inputs; accepts NCHW and transposes on entry
    (reference models.py:122-202 with torch's NCHW)."""

    channels: Sequence[int]
    kernel_sizes: Sequence[int]
    strides: Sequence[int]
    paddings: Union[str, Sequence[int]] = "VALID"
    activation: Union[None, str, Callable] = "relu"
    layer_norm: bool = False
    input_channel_first: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = resolve_activation(self.activation)
        if self.input_channel_first:
            x = jnp.moveaxis(x, -3, -1)  # NCHW -> NHWC
        x = x.astype(self.dtype)
        for i, (ch, k, s) in enumerate(zip(self.channels, self.kernel_sizes, self.strides)):
            # sym_pad: the symmetric per-side padding when expressible as one int
            # (every non-string config here is), else None (e.g. "SAME")
            if isinstance(self.paddings, str):
                padding = self.paddings
                sym_pad = 0 if padding == "VALID" else None
            else:
                p = self.paddings[i] if not isinstance(self.paddings, int) else self.paddings
                padding = [(p, p), (p, p)]
                sym_pad = p
            # stride-2 even-k convs with VALID or symmetric-int padding (the
            # Dreamer encoder stages) take the CPU fast-gradient decomposition
            # (ops/conv.py; TPU keeps the native conv). Explicit names keep the
            # nn.Conv parameter tree.
            if sym_pad is not None and s == 2 and k % 2 == 0:
                x = FastConv2x(
                    features=ch, kernel_size=k, padding=sym_pad, dtype=self.dtype, name=f"Conv_{i}"
                )(x)
            else:
                x = nn.Conv(
                    ch, (k, k), strides=(s, s), padding=padding, dtype=self.dtype, name=f"Conv_{i}"
                )(x)
            if self.layer_norm:
                x = nn.LayerNorm(dtype=self.dtype, epsilon=1e-3)(x)  # NHWC: normalize channels
            x = act(x)
        return x


class DeCNN(nn.Module):
    """Transposed-conv stack over NHWC latents, producing NCHW outputs to match the
    buffer layout (reference models.py:205-285)."""

    channels: Sequence[int]
    kernel_sizes: Sequence[int]
    strides: Sequence[int]
    paddings: Union[str, Sequence[int]] = "VALID"
    activation: Union[None, str, Callable] = "relu"
    layer_norm: bool = False
    output_channel_first: bool = True
    final_activation: Union[None, str, Callable] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = resolve_activation(self.activation)
        n = len(self.channels)
        for i, (ch, k, s) in enumerate(zip(self.channels, self.kernel_sizes, self.strides)):
            if isinstance(self.paddings, str):
                padding = self.paddings
            else:
                p = self.paddings[i] if not isinstance(self.paddings, int) else self.paddings
                padding = [(p, p), (p, p)]
            x = nn.ConvTranspose(ch, (k, k), strides=(s, s), padding=padding, dtype=self.dtype)(x)
            last = i == n - 1
            if not last:
                if self.layer_norm:
                    x = nn.LayerNorm(dtype=self.dtype, epsilon=1e-3)(x)
                x = act(x)
            elif self.final_activation is not None:
                x = resolve_activation(self.final_activation)(x)
        if self.output_channel_first:
            x = jnp.moveaxis(x, -1, -3)  # NHWC -> NCHW
        return x


class NatureCNN(nn.Module):
    """The classic DQN encoder (reference models.py:288-328): 32/64/64 convs + dense."""

    features_dim: int
    screen_size: int = 64
    in_channels: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = CNN(
            channels=(32, 64, 64),
            kernel_sizes=(8, 4, 3),
            strides=(4, 2, 1),
            paddings="VALID",
            activation="relu",
            dtype=self.dtype,
        )(x)
        x = jnp.reshape(x, (*x.shape[:-3], -1))
        x = nn.Dense(self.features_dim, dtype=self.dtype)(x)
        return jax.nn.relu(x)


class LayerNormGRUCell(nn.Module):
    """GRU cell with layer-norm applied to the stacked input/recurrent projection
    (reference models.py:331-411: norm after the input projection, before gating).

    One fused matmul computes all three gates — the shape the MXU wants. Usable as a
    ``lax.scan`` body for full-sequence unrolls.
    """

    hidden_size: int
    bias: bool = True
    batch_first: bool = False
    layer_norm: bool = True
    layer_norm_eps: float = 1e-3
    kernel_init: Optional[Callable] = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, hx: jax.Array, x: jax.Array) -> jax.Array:
        inp = jnp.concatenate([x, hx], axis=-1).astype(self.dtype)
        kernel_init = self.kernel_init or nn.initializers.lecun_normal()
        # params stay float32 (flax's param_dtype convention — bf16-mixed keeps f32
        # master weights); self.dtype only selects the COMPUTE dtype
        w = self.param("kernel", kernel_init, (inp.shape[-1], 3 * self.hidden_size), jnp.float32)
        b = (
            self.param("bias", nn.initializers.zeros_init(), (3 * self.hidden_size,), jnp.float32)
            if self.bias
            else jnp.zeros((3 * self.hidden_size,), jnp.float32)
        )
        w = w.astype(self.dtype)
        b = b.astype(self.dtype)
        if self.layer_norm:
            scale = self.param(
                "ln_scale", nn.initializers.ones_init(), (3 * self.hidden_size,), jnp.float32
            )
            offset = self.param(
                "ln_bias", nn.initializers.zeros_init(), (3 * self.hidden_size,), jnp.float32
            )
            # the fused Pallas step (matmul + layernorm + gating in one VMEM pass)
            # applies when lowering for TPU with the weight block VMEM-resident; any
            # other lowering platform (e.g. the CPU-pinned act path of a TPU run)
            # takes the XLA path — same math, parity-tested in tests/test_ops.
            # The platform_dependent branch is built only when the PROCESS backend is
            # TPU: lax.cond lowers every branch regardless of the selected platform,
            # so on a CPU-only process the Pallas branch would fail to lower ("Only
            # interpret mode is supported on CPU backend") even though it can never
            # be taken. Known limitation (pre-existing, unchanged by this gate): in a
            # TPU process a jit pinned to backend="cpu" (the ActPlacement act path)
            # still lowers the Pallas branch for CPU and hits the same error — run
            # such programs with SHEEPRL_DISABLE_PALLAS=1 until the dispatch keys on
            # the lowering platform instead of the process backend.
            import os

            from sheeprl_tpu import ops

            hx_d = hx.astype(self.dtype)
            if (
                inp.ndim == 2
                and ops.pallas_gru_applicable(inp.shape[-1], self.hidden_size)
                and os.environ.get("SHEEPRL_DISABLE_PALLAS", "0") != "1"
                and jax.default_backend() == "tpu"
                # Pallas kernels don't partition: a multi-device mesh (dp or
                # model-sharded GRU kernel) must take the XLA path
                and not ops.partitioned_mesh_active()
            ):
                return jax.lax.platform_dependent(
                    tpu=lambda: ops.fused_ln_gru_step(
                        inp, hx_d, w, b, scale, offset, eps=self.layer_norm_eps
                    ),
                    default=lambda: ops.ln_gru_step_reference(
                        inp, hx_d, w, b, scale, offset, eps=self.layer_norm_eps
                    ),
                ).astype(self.dtype)
            return ops.ln_gru_step_reference(
                inp, hx_d, w, b, scale, offset, eps=self.layer_norm_eps
            ).astype(self.dtype)
        gates = inp @ w + b
        reset, cand, update = jnp.split(gates, 3, axis=-1)
        reset = jax.nn.sigmoid(reset)
        cand = jnp.tanh(reset * cand)
        update = jax.nn.sigmoid(update - 1)
        return update * cand + (1 - update) * hx


class MultiEncoder(nn.Module):
    """Fuse per-key cnn/mlp encoders over a dict observation
    (reference models.py:413-475): outputs are concatenated feature vectors."""

    cnn_encoder: Optional[nn.Module]
    mlp_encoder: Optional[nn.Module]

    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        outs = []
        if self.cnn_encoder is not None:
            outs.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            outs.append(self.mlp_encoder(obs))
        if not outs:
            raise ValueError("there must be at least one encoder (cnn or mlp)")
        return jnp.concatenate(outs, axis=-1)


class MultiDecoder(nn.Module):
    """Per-key cnn/mlp decoders from a shared latent (reference models.py:478-504)."""

    cnn_decoder: Optional[nn.Module]
    mlp_decoder: Optional[nn.Module]

    def __call__(self, latents: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            out.update(self.cnn_decoder(latents))
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latents))
        return out
