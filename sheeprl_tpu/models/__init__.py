from sheeprl_tpu.models.models import (
    CNN,
    MLP,
    DeCNN,
    LayerNormGRUCell,
    MultiDecoder,
    MultiEncoder,
    NatureCNN,
    resolve_activation,
)

__all__ = [
    "CNN",
    "MLP",
    "DeCNN",
    "LayerNormGRUCell",
    "MultiDecoder",
    "MultiEncoder",
    "NatureCNN",
    "resolve_activation",
]
