"""Attribute-access dict used as the composed-config container.

Equivalent role to the reference's ``dotdict`` (sheeprl/utils/utils.py:34-60): after
composition the config becomes a plain recursive dict so framework code is free of any
config-library types.
"""

from __future__ import annotations

from typing import Any, Dict


class dotdict(dict):
    """A dict whose items are also reachable as attributes, recursively."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            self[k] = self._wrap(v)

    @classmethod
    def _wrap(cls, value: Any) -> Any:
        if isinstance(value, dotdict):
            return value
        if isinstance(value, dict):
            return cls(value)
        if isinstance(value, (list, tuple)):
            return type(value)(cls._wrap(v) for v in value)
        return value

    def __setitem__(self, key: str, value: Any) -> None:
        super().__setitem__(key, self._wrap(value))

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def __delattr__(self, name: str) -> None:
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def setdefault(self, key: str, default: Any = None) -> Any:
        if key not in self:
            self[key] = default
        return self[key]

    def update(self, *args: Any, **kwargs: Any) -> None:  # type: ignore[override]
        other: Dict[str, Any] = dict(*args, **kwargs)
        for k, v in other.items():
            self[k] = v

    def copy(self) -> "dotdict":
        return dotdict({k: v for k, v in self.items()})

    def as_dict(self) -> Dict[str, Any]:
        """Deep-convert back to plain builtin containers (for YAML/ckpt dumps)."""

        def unwrap(v: Any) -> Any:
            if isinstance(v, dict):
                return {k: unwrap(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [unwrap(x) for x in v]
            return v

        return unwrap(self)


def get_by_path(cfg: dict, path: str, default: Any = ...) -> Any:
    """Fetch ``a.b.c`` from nested dicts; raises KeyError unless a default is given."""
    node: Any = cfg
    for part in path.split("."):
        if isinstance(node, dict) and part in node:
            node = node[part]
        elif isinstance(node, (list, tuple)) and part.lstrip("-").isdigit():
            node = node[int(part)]
        else:
            if default is ...:
                raise KeyError(path)
            return default
    return node


def set_by_path(cfg: dict, path: str, value: Any, *, create: bool = True) -> None:
    parts = path.split(".")
    node: Any = cfg
    for part in parts[:-1]:
        if not isinstance(node, dict):
            raise KeyError(f"cannot descend into non-dict at {part!r} of {path!r}")
        if part not in node:
            if not create:
                raise KeyError(path)
            node[part] = {}
        node = node[part]
    if not create and parts[-1] not in node:
        raise KeyError(
            f"unknown config key {path!r} (use +{path}=... to add a new key)"
        )
    node[parts[-1]] = value
