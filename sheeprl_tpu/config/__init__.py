from sheeprl_tpu.config.composer import (
    Composer,
    ConfigError,
    MissingMandatoryValue,
    compose,
    deep_merge,
    explicit_overrides,
)
from sheeprl_tpu.config.dotdict import dotdict, get_by_path, set_by_path
from sheeprl_tpu.config.instantiate import instantiate, locate

__all__ = [
    "Composer",
    "ConfigError",
    "MissingMandatoryValue",
    "compose",
    "deep_merge",
    "explicit_overrides",
    "dotdict",
    "get_by_path",
    "set_by_path",
    "instantiate",
    "locate",
]
