"""Self-contained YAML config composition — the framework's L1 layer.

Plays the role Hydra plays in the reference (sheeprl/cli.py:357,
sheeprl/configs/config.yaml:4-15, hydra_plugins/sheeprl_search_path.py:23-32) without
depending on hydra/omegaconf (not available in this environment). Semantics kept:

- a root ``config.yaml`` with a ``defaults`` list of config *groups* (``algo: default``),
  composed in order with ``_self_`` marking where the root body merges;
- experiment files (``exp/*.yaml``) that are global overlays and may themselves carry a
  ``defaults`` list with ``override /group: option`` entries;
- dotted CLI overrides ``a.b.c=value`` (YAML-typed), group selection ``group=option``,
  additions ``+a.b=value`` and deletions ``~a.b``;
- ``${a.b.c}`` interpolation (whole-value refs keep their type; embedded refs become
  strings) plus ``${now:FORMAT}`` timestamps and ``${oc.env:VAR,default}`` env reads;
- a search-path extension hook via ``SHEEPRL_SEARCH_PATH`` (``;``-separated directories,
  ``file://`` prefix allowed) so user config trees can shadow/extend the builtin one.
"""

from __future__ import annotations

import datetime
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import yaml

from sheeprl_tpu.config.dotdict import dotdict, get_by_path, set_by_path

_BUILTIN_CONFIG_DIR = Path(__file__).resolve().parent.parent / "configs"
_INTERP_RE = re.compile(r"\$\{([^{}]+)\}")


class ConfigError(Exception):
    pass


class MissingMandatoryValue(ConfigError):
    pass


def _search_dirs(extra: Optional[Sequence[os.PathLike]] = None) -> List[Path]:
    """User dirs (SHEEPRL_SEARCH_PATH) shadow the builtin tree, like the reference's
    search-path plugin (hydra_plugins/sheeprl_search_path.py:23-32)."""
    dirs: List[Path] = []
    env = os.environ.get("SHEEPRL_SEARCH_PATH", "")
    for entry in [e for e in env.split(";") if e.strip()]:
        entry = entry.strip()
        if entry.startswith("file://"):
            entry = entry[len("file://") :]
        if entry.startswith("pkg://"):
            # pkg://a.b.c → site dir of that package
            mod = entry[len("pkg://") :].replace(".", "/")
            for root in map(Path, __import__("sys").path):
                if (root / mod).is_dir():
                    dirs.append(root / mod)
                    break
            continue
        dirs.append(Path(entry))
    if extra:
        dirs.extend(Path(e) for e in extra)
    dirs.append(_BUILTIN_CONFIG_DIR)
    return [d for d in dirs if d.is_dir()]


def _find_config(group: str, name: str, dirs: List[Path]) -> Optional[Path]:
    name = str(name)
    if not name.endswith(".yaml"):
        name += ".yaml"
    for d in dirs:
        p = d / group / name if group else d / name
        if p.is_file():
            return p
    return None


class _SciFloatLoader(yaml.SafeLoader):
    """SafeLoader that also resolves '1e-3'-style scalars as floats (YAML 1.1 only
    accepts '1.0e-3'), matching what hydra/omegaconf users expect."""


_SciFloatLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:[-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
        |\.[0-9][0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?\.(?:inf|Inf|INF)
        |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def yaml_load(stream: Any) -> Any:
    return yaml.load(stream, Loader=_SciFloatLoader)


def _load_yaml(path: Path) -> Dict[str, Any]:
    with open(path) as f:
        data = yaml_load(f)
    return data or {}


def deep_merge(base: Dict[str, Any], other: Dict[str, Any]) -> Dict[str, Any]:
    """Merge ``other`` into ``base`` (returns base). Dicts merge recursively; any other
    value (including lists) replaces."""
    for k, v in other.items():
        if k in base and isinstance(base[k], dict) and isinstance(v, dict):
            deep_merge(base[k], v)
        else:
            base[k] = v
    return base


def _parse_defaults(defaults: List[Any]) -> List[Tuple[str, Any, bool]]:
    """Normalize a defaults list to [(group, option, is_override)]; '_self_' becomes
    ('_self_', None, False)."""
    out: List[Tuple[str, Any, bool]] = []
    for entry in defaults or []:
        if entry == "_self_":
            out.append(("_self_", None, False))
        elif isinstance(entry, str):
            # bare base ref (e.g. `- dreamer_v3` inside an exp/algo file)
            out.append((entry, None, False))
        elif isinstance(entry, dict):
            (key, val), = entry.items()
            override = False
            key = str(key)
            if key.startswith("override "):
                override = True
                key = key[len("override ") :]
            key = key.strip().lstrip("/")
            out.append((key, val, override))
        else:
            raise ConfigError(f"unsupported defaults entry: {entry!r}")
    return out


class Composer:
    def __init__(self, extra_dirs: Optional[Sequence[os.PathLike]] = None) -> None:
        self.dirs = _search_dirs(extra_dirs)

    def available(self, group: str) -> List[str]:
        names: List[str] = []
        for d in self.dirs:
            g = d / group
            if g.is_dir():
                names.extend(p.stem for p in g.glob("*.yaml"))
        return sorted(set(names))

    def compose(self, overrides: Sequence[str] = (), config_name: str = "config") -> dotdict:
        group_sel, dotted, additions, deletions = self._split_overrides(overrides)

        root_path = _find_config("", config_name, self.dirs)
        if root_path is None:
            raise ConfigError(f"root config {config_name!r} not found in {self.dirs}")
        root = _load_yaml(root_path)
        defaults = _parse_defaults(root.pop("defaults", []))

        # CLI group selections override the root defaults list.
        defaults = [
            ("_self_", None, False) if g == "_self_" else (g, group_sel.get(g, opt), ov)
            for g, opt, ov in defaults
        ]
        known_groups = {g for g, _, _ in defaults if g != "_self_"}
        for g, opt in group_sel.items():
            if g not in known_groups:
                defaults.append((g, opt, False))

        cfg: Dict[str, Any] = {}
        self._compose_defaults(cfg, defaults, root_body=root, group_sel=group_sel)

        for path, value in dotted.items():
            set_by_path(cfg, path, value, create=False)
        for path, value in additions.items():
            set_by_path(cfg, path, value, create=True)
        for path in deletions:
            try:
                parent = get_by_path(cfg, ".".join(path.split(".")[:-1])) if "." in path else cfg
                parent.pop(path.split(".")[-1], None)
            except KeyError:
                pass

        cfg = resolve_interpolations(cfg)
        _check_mandatory(cfg)
        return dotdict(cfg)

    # -- internals ---------------------------------------------------------------

    def _compose_defaults(
        self,
        cfg: Dict[str, Any],
        defaults: List[Tuple[str, Any, bool]],
        root_body: Dict[str, Any],
        group_sel: Dict[str, str],
    ) -> None:
        # First pass: let 'exp' (or any global overlay) rewrite earlier group choices via
        # its own `override /group: option` defaults.
        resolved: List[Tuple[str, Any]] = []
        overlay_bodies: List[Dict[str, Any]] = []
        pending = list(defaults)
        overrides_from_overlays: Dict[str, Any] = {}
        for group, option, _ in pending:
            if group == "_self_":
                resolved.append(("_self_", None))
                continue
            if option is None or option == "???":
                if group in ("exp",):
                    raise MissingMandatoryValue(
                        "You must specify an experiment: e.g. `exp=ppo` "
                        f"(available: {', '.join(self.available('exp'))})"
                    )
                continue
            if group == "exp" or self._is_global_overlay(group, option):
                body, overlay_overrides = self._load_overlay(group, option)
                for g2, o2 in overlay_overrides:
                    overrides_from_overlays[g2] = group_sel.get(g2, o2)
                overlay_bodies.append(body)
            else:
                resolved.append((group, option))

        for group, option in resolved:
            if group == "_self_":
                deep_merge(cfg, root_body)
                continue
            option = overrides_from_overlays.pop(group, option)
            self._merge_group(cfg, group, option)
        # groups introduced only by the overlay
        for group, option in overrides_from_overlays.items():
            self._merge_group(cfg, group, option)
        for body in overlay_bodies:
            deep_merge(cfg, body)

    def _is_global_overlay(self, group: str, option: Any) -> bool:
        path = _find_config(group, option, self.dirs)
        if path is None:
            return False
        with open(path) as f:
            head = f.readline()
        return "@package _global_" in head

    def _load_overlay(
        self, group: str, option: Any, _depth: int = 0
    ) -> Tuple[Dict[str, Any], List[Tuple[str, Any]]]:
        """Load a ``@package _global_`` overlay (an exp file). Returns (body, overrides)
        where overrides is a list of (group, option) selections the overlay forces on the
        root defaults (``override /algo: ppo``). Overlays may inherit other overlays of
        the same group via a bare ``- name`` defaults entry."""
        if _depth > 10:
            raise ConfigError(f"overlay recursion too deep at {group}/{option}")
        path = _find_config(group, option, self.dirs)
        if path is None:
            raise ConfigError(
                f"config '{group}/{option}' not found; available: {self.available(group)}"
            )
        body = _load_yaml(path)
        sub_defaults = _parse_defaults(body.pop("defaults", []))
        merged: Dict[str, Any] = {}
        overrides: List[Tuple[str, Any]] = []
        for g, o, is_override in sub_defaults:
            if g == "_self_":
                continue
            if is_override:
                overrides.append((g, o))
            elif o is None:
                base_body, base_overrides = self._load_overlay(group, g, _depth + 1)
                deep_merge(merged, base_body)
                overrides = base_overrides + overrides
            elif "@" in g:
                src, _, pkg = g.partition("@")
                sub = self._load_group_node(src.rstrip("/"), o)
                if pkg != "_global_":
                    for part in reversed(pkg.split(".")):
                        sub = {part: sub}
                deep_merge(merged, sub)
            else:
                overrides.append((g, o))
        deep_merge(merged, body)
        return merged, overrides

    def _merge_group(self, cfg: Dict[str, Any], group: str, option: Any) -> None:
        if option is None:
            return
        node = self._load_group_node(group, option)
        deep_merge(cfg, {group: node} if group != "_global_" else node)

    def _load_group_node(self, group: str, option: Any, _depth: int = 0) -> Dict[str, Any]:
        """Load ``group/option.yaml``, recursively resolving its ``defaults`` list.

        Supported defaults entries inside a group file:
          - ``_self_`` — merge point for the file body;
          - ``name`` (bare, via {name: null}? no — expressed as ``- name: null``)…
            practically: ``- default`` style sugar is written as ``{default: null}`` by
            YAML, so a null option means "option of the same group named <key>";
          - ``other_option`` of the same group (inheritance), e.g. ``- dreamer_v3``;
          - ``/other_group@package: option`` — load another group's option under
            ``package`` inside this node (the reference's ``/optim@optimizer: adam``).
        """
        if _depth > 10:
            raise ConfigError(f"defaults recursion too deep at {group}/{option}")
        path = _find_config(group, option, self.dirs)
        if path is None:
            raise ConfigError(
                f"config '{group}/{option}' not found; available: {self.available(group)}"
            )
        body = _load_yaml(path)
        raw_defaults = body.pop("defaults", [])
        node: Dict[str, Any] = {}
        merged_self = False
        for entry in raw_defaults or []:
            if entry == "_self_":
                deep_merge(node, body)
                merged_self = True
                continue
            if isinstance(entry, str):
                # bare string: an option of the same group used as a base
                deep_merge(node, self._load_group_node(group, entry, _depth + 1))
                continue
            (key, val), = entry.items()
            key = str(key).strip().lstrip("/")
            if "@" in key:
                src, _, pkg = key.partition("@")
                sub = self._load_group_node(src.rstrip("/"), val, _depth + 1)
                if pkg != "_global_":
                    # dotted packages nest (`/optim@actor.optimizer: adam`)
                    for part in reversed(pkg.split(".")):
                        sub = {part: sub}
                deep_merge(node, sub)
            elif val is None:
                deep_merge(node, self._load_group_node(group, key, _depth + 1))
            else:
                deep_merge(node, self._load_group_node(key, val, _depth + 1))
        if not merged_self:
            deep_merge(node, body)
        return node

    def _is_group(self, name: str) -> bool:
        return any((d / name).is_dir() for d in self.dirs)

    def _split_overrides(
        self,
        overrides: Sequence[str],
    ) -> Tuple[Dict[str, str], Dict[str, Any], Dict[str, Any], List[str]]:
        group_sel: Dict[str, str] = {}
        dotted: Dict[str, Any] = {}
        additions: Dict[str, Any] = {}
        deletions: List[str] = []
        for raw in overrides:
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("~"):
                deletions.append(raw[1:])
                continue
            if "=" not in raw:
                raise ConfigError(f"override {raw!r} is not of the form key=value")
            key, value = raw.split("=", 1)
            is_add = key.startswith("+")
            key = key.lstrip("+")
            parsed = yaml_load(value) if value != "" else None
            if is_add:
                additions[key] = parsed
            elif "." not in key and self._is_group(key):
                # bare `group=option`: group selection (a dir of that name exists)
                group_sel[key] = value
            else:
                dotted[key] = parsed
        return group_sel, dotted, additions, deletions


def resolve_interpolations(cfg: Dict[str, Any]) -> Dict[str, Any]:
    def resolve_value(value: Any, depth: int = 0) -> Any:
        if depth > 20:
            raise ConfigError("interpolation loop detected")
        if isinstance(value, str):
            m = _INTERP_RE.fullmatch(value.strip())
            if m:
                return resolve_ref(m.group(1), depth)
            def sub(match: "re.Match[str]") -> str:
                return str(resolve_ref(match.group(1), depth))
            return _INTERP_RE.sub(sub, value)
        if isinstance(value, dict):
            return {k: resolve_value(v, depth) for k, v in value.items()}
        if isinstance(value, list):
            return [resolve_value(v, depth) for v in value]
        return value

    def resolve_ref(ref: str, depth: int) -> Any:
        ref = ref.strip()
        if ref.startswith("now:"):
            return datetime.datetime.now().strftime(ref[len("now:") :])
        if ref.startswith("oc.env:") or ref.startswith("env:"):
            body = ref.split(":", 1)[1]
            var, _, default = body.partition(",")
            var = var.strip()
            if var in os.environ:
                return os.environ[var]
            # YAML-style scalars in the DEFAULT position keep their type (null/bool/
            # int/float); a set env var always passes through as a raw string
            # (OmegaConf parity: ${oc.env:VAR,null} -> None only when VAR is unset)
            default = default.strip()
            if default in ("null", "None"):
                return None
            if default in ("true", "false"):
                return default == "true"
            # YAML number forms only — python-only spellings (nan/inf/1_000) stay
            # strings, matching OmegaConf
            if re.fullmatch(r"[+-]?\d+", default):
                return int(default)
            if re.fullmatch(r"[+-]?(\d+\.\d*|\.\d+|\d+)([eE][+-]?\d+)?", default):
                return float(default)
            return default
        try:
            return resolve_value(get_by_path(cfg, ref), depth + 1)
        except KeyError:
            raise ConfigError(f"interpolation ${{{ref}}} not found") from None

    return resolve_value(cfg)  # type: ignore[return-value]


def _check_mandatory(cfg: Dict[str, Any], prefix: str = "") -> None:
    for k, v in cfg.items():
        path = f"{prefix}{k}"
        if isinstance(v, dict):
            _check_mandatory(v, path + ".")
        elif v == "???":
            raise MissingMandatoryValue(f"mandatory config value {path} is not set")


def compose(
    overrides: Sequence[str] = (),
    config_name: str = "config",
    extra_dirs: Optional[Sequence[os.PathLike]] = None,
) -> dotdict:
    return Composer(extra_dirs).compose(overrides, config_name)


def explicit_overrides(overrides: Sequence[str]) -> Dict[str, Any]:
    """The dotted-key → parsed-value map of the user's EXPLICIT value overrides
    (``a.b=c`` and ``+a.b=c``; group selections and deletions excluded). The
    resume merge re-applies these over a restored config — something the user
    typed on this launch's command line always beats the checkpoint's saved
    value (``cli.resume_from_checkpoint``, ``resilience/supervisor.py``)."""
    group_sel, dotted, additions, _ = Composer()._split_overrides(overrides)
    merged = dict(dotted)
    merged.update(additions)
    return merged
