"""``_target_``-driven object construction, the DI mechanism of the config tree.

Mirrors the role of ``hydra.utils.instantiate`` in the reference (optimizers at
sheeprl/algos/ppo/ppo.py:183, env wrappers at sheeprl/utils/env.py:74): a config node
whose ``_target_`` names a dotted callable is imported and called with the node's other
keys as kwargs.
"""

from __future__ import annotations

import importlib
from typing import Any, Callable, Dict


def locate(path: str) -> Any:
    module_path, _, attr = path.rpartition(".")
    if not module_path:
        raise ImportError(f"cannot locate bare name {path!r}")
    try:
        module = importlib.import_module(module_path)
        return getattr(module, attr)
    except (ImportError, AttributeError):
        # maybe the attr is a nested class: walk from the longest importable prefix
        parts = path.split(".")
        for i in range(len(parts) - 1, 0, -1):
            try:
                obj: Any = importlib.import_module(".".join(parts[:i]))
            except ImportError:
                continue
            for p in parts[i:]:
                obj = getattr(obj, p)
            return obj
        raise


def instantiate(node: Dict[str, Any], *args: Any, **overrides: Any) -> Any:
    if node is None:
        return None
    if not isinstance(node, dict) or "_target_" not in node:
        raise ValueError(f"cannot instantiate non-_target_ node: {node!r}")
    target: Callable = locate(node["_target_"])
    kwargs = {k: v for k, v in node.items() if not (k.startswith("_") and k.endswith("_"))}
    partial = bool(node.get("_partial_", False))
    kwargs.update(overrides)
    if partial:
        import functools

        return functools.partial(target, *args, **kwargs)
    return target(*args, **kwargs)
