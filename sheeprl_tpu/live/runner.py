"""``python sheeprl.py live <spec> [key=value ...]`` — the closed-loop flywheel.

One supervised in-process gang that closes the production RL loop:

- **serve**: ``spec.servers`` :class:`~sheeprl_tpu.serve.server.PolicyServer`
  roles boot from ``checkpoint_path`` and drive real env sessions (the serve
  driver's traffic pattern). Serving slots double as actors: each finished
  session's trajectory is assembled OFF the tick loop
  (``serve/trajectory.py``) and shipped through an
  :class:`~sheeprl_tpu.data.service.ExperienceWriter` — slot ``rank k`` is
  actor rank ``k`` of the experience plane. Explore slots
  (``serve.explore.fraction``) inject session-seeded action noise; the
  remaining "real traffic" slots stay greedy and byte-exact.
- **learn**: ONE experience-service learner (the ``buffer.backend=service``
  learner of ``sac_decoupled``, verbatim) ingests those trajectories, trains
  continuously at ``algo.replay_ratio`` and publishes actor weights every
  ``buffer.service.publish_every`` rounds on the version-keyed weight plane.
- **reload**: every server's :class:`~sheeprl_tpu.serve.reload.WeightReloader`
  follows the plane via ``SubscriberReloadSource`` — new versions hot-swap
  between ticks, zero recompiles (same avals ⇒ same compiled step program).
  ``buffer.service.poll_weights=false`` freezes serving weights (and makes
  ``diagnose``'s weight_staleness detector fire, by design).

The roles share one process: the coordination plane is an in-process
:class:`~sheeprl_tpu.data.service.LocalKV`
(:func:`~sheeprl_tpu.data.service.install_local_service_plane`), the learner
runs on a worker thread with its own Fabric, and the whole gang is supervised
by the training supervisor's ``run_restart_policy`` — a crashed attempt
restarts the WHOLE flywheel (fresh plane, fresh roles) within the restart
budget. SIGTERM drains every server inside ``drain_grace_s``, lets the learner
take its emergency checkpoint, and exits ``75`` — lifecycle parity with
training and serving.

Telemetry: serve role 0 writes ``telemetry.jsonl``, role ``k>0``
``telemetry.serve{k}.jsonl``, the learner ``telemetry.learner.jsonl``, and the
gang supervisor ``telemetry.live.jsonl`` (``live`` lifecycle events +
restart/giveup) — all in the live dir, so ``watch``/``diagnose``/``trace``
stitch the session→ingest→train→publish→reload flow across role tracks.

Exit codes: ``0`` every session completed and the learner exited cleanly,
``1`` a role crashed (restart budget exhausted when supervised), ``2`` nothing
to drive, ``75`` SIGTERM → drained cleanly.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["live_main"]

# a learner that outlives the serve roles' shutdown by this much is hung
_LEARNER_JOIN_S = 600.0


def _default_live_dir(spec: Dict[str, Any]) -> str:
    stamp = time.strftime("%Y-%m-%d_%H-%M-%S")
    return os.path.join("logs", "live", f"{spec['name']}_{stamp}")


def _learner_cfg(cfg: Any, spec: Dict[str, Any], live_dir: str) -> Any:
    """Derive the learner's config from the serving config: same checkpoint
    config (so avals — and therefore the compiled serving program — match the
    published weights), retargeted at the service backend and the live dir's
    role stream."""
    import copy

    import yaml

    from sheeprl_tpu.config import dotdict, set_by_path

    lcfg = dotdict(copy.deepcopy(dict(cfg)))
    set_by_path(lcfg, "buffer.backend", "service", create=True)
    set_by_path(lcfg, "buffer.service.actors", int(spec["servers"]), create=True)
    # the learner starts FRESH from cfg.seed (same init as training would) and
    # immediately publishes v1 — a spec learner override of
    # checkpoint.resume_from warm-starts it from a checkpoint instead
    set_by_path(lcfg, "checkpoint.resume_from", None, create=True)
    set_by_path(lcfg, "metric.telemetry.enabled", True, create=True)
    set_by_path(lcfg, "metric.telemetry.jsonl", True, create=True)
    set_by_path(
        lcfg,
        "metric.telemetry.jsonl_path",
        os.path.join(live_dir, "telemetry.jsonl"),
        create=True,
    )
    for item in spec["learner"]:
        if "=" not in item:
            raise ValueError(f"live spec learner override {item!r} must be key=value")
        key, raw = item.split("=", 1)
        try:
            value = yaml.safe_load(raw)
        except yaml.YAMLError:
            value = raw
        set_by_path(lcfg, key, value, create=True)
    return lcfg


class _ActorGraftSource:
    """The experience plane publishes the ACTOR subtree only (the decoupled
    learner's actors never need critic/temperature params), while a serve
    policy holds the family's FULL serving tree. Graft each polled subtree
    into the server's current params so the reloader's aval gate compares
    like with like; payloads that already match the full tree pass through."""

    name = "subscriber"

    def __init__(self, inner: Any, server: Any) -> None:
        self._inner = inner
        self._server = server

    def peek_available(self) -> Any:
        return self._inner.peek_available()

    def poll(self) -> Any:
        out = self._inner.poll()
        if out is None:
            return None
        tree, version, meta = out
        current = self._server.policy.params
        if (
            isinstance(current, dict)
            and "actor" in current
            and not (isinstance(tree, dict) and set(tree) == set(current))
        ):
            merged = dict(current)
            merged["actor"] = tree
            tree = merged
        return tree, version, meta


class _LiveRole:
    """One serving role of the gang: server + its trajectory ingest, weight
    subscription/reloader, dataflow lineage and per-role telemetry stream."""

    def __init__(
        self,
        rank: int,
        cfg: Any,
        fabric: Any,
        state: Any,
        live_dir: str,
        spec: Dict[str, Any],
        *,
        kv: Any,
        ns: str,
        opts: Dict[str, Any],
        attempt: int,
    ) -> None:
        from sheeprl_tpu.config import dotdict
        from sheeprl_tpu.data.service import ActorDataflow, ExperienceWriter, WeightSubscriber
        from sheeprl_tpu.resilience.faults import build_fault_plan
        from sheeprl_tpu.serve.policy import resolve_serve_policy
        from sheeprl_tpu.serve.reload import SubscriberReloadSource, WeightReloader
        from sheeprl_tpu.serve.server import PolicyServer
        from sheeprl_tpu.serve.telemetry import ServingTelemetry
        from sheeprl_tpu.serve.trajectory import TrajectoryIngest

        self.rank = int(rank)
        # each role drives sessions from its own seed plane (session seed =
        # cfg.seed + client index inside run_env_sessions)
        self.cfg = dotdict(dict(cfg))
        self.cfg["seed"] = int(cfg.seed) + self.rank * 10000
        serve_cfg = cfg.serve
        tcfg = serve_cfg.get("telemetry") or {}

        policy = resolve_serve_policy(fabric, cfg, state)
        stream = "telemetry.jsonl" if self.rank == 0 else f"telemetry.serve{self.rank}.jsonl"
        self.telemetry = ServingTelemetry(
            fabric,
            cfg,
            live_dir,
            enabled=bool(tcfg.get("enabled", True)),
            every=int(tcfg.get("every", 256)),
            attempt=attempt,
            rank=self.rank,
            jsonl_path=os.path.join(live_dir, stream),
            serve_info={
                "role": "serve",
                "rank": self.rank,
                "slots": int(serve_cfg.slots),
                "max_batch_wait_ms": float(serve_cfg.max_batch_wait_ms),
                "greedy": bool(serve_cfg.greedy),
                "checkpoint_path": str(cfg.checkpoint_path),
                **policy.meta,
            },
        )
        self.server = PolicyServer(
            policy,
            slots=int(serve_cfg.slots),
            max_batch_wait_ms=float(serve_cfg.max_batch_wait_ms),
            base_seed=int(self.cfg.seed),
            telemetry=self.telemetry,
            request_timeout=float(serve_cfg.request_timeout),
            max_queue=serve_cfg.get("max_queue"),
            deadline_ms=serve_cfg.get("deadline_ms"),
            degraded_wait_factor=float(serve_cfg.get("degraded_wait_factor") or 4.0),
            fault_plan=build_fault_plan(cfg.get("resilience")),
            explore_fraction=float((serve_cfg.get("explore") or {}).get("fraction") or 0.0),
            explore_noise=float((serve_cfg.get("explore") or {}).get("noise") or 0.3),
        )
        self.writer = ExperienceWriter(
            kv,
            ns,
            self.rank,
            max_inflight=opts["max_inflight"],
            flush_every=opts["flush_every"],
            poll_s=opts["poll_s"],
            timeout_s=opts["timeout_s"],
            abort_check=opts["abort_check"],
        )
        self.ingest = TrajectoryIngest(
            self.writer,
            mlp_keys=cfg.algo.mlp_keys.encoder,
            max_queue=int(spec["ingest"]["max_queue"]),
            sample_next_obs=bool(cfg.buffer.sample_next_obs),
            telemetry=self.telemetry,
            weight_version_of=lambda: self.server.weight_version,
        )
        self.server.trajectories = self.ingest
        self.subscriber = WeightSubscriber(
            kv, ns, poll_s=opts["poll_s"], timeout_s=opts["timeout_s"], abort_check=opts["abort_check"]
        )
        self.telemetry.attach_dataflow(ActorDataflow(self.writer, self.subscriber))
        self.reloader = None
        if bool(opts.get("poll_weights", True)):
            self.reloader = WeightReloader(
                self.server,
                _ActorGraftSource(SubscriberReloadSource(self.subscriber), self.server),
                telemetry=self.telemetry,
                poll_s=float(spec["reload_poll_s"]),
            )
        self.results: List[Dict[str, Any]] = []
        self.error: Optional[BaseException] = None

    def start(self) -> None:
        self.server.start()
        if self.reloader is not None:
            self.reloader.start()

    def drive(self, spec: Dict[str, Any], live_dir: str) -> None:
        """Run the role's session waves (the driver thread's body)."""
        from sheeprl_tpu.config import dotdict
        from sheeprl_tpu.resilience import signals
        from sheeprl_tpu.serve.drivers import run_env_sessions

        pause = float(spec["wave_pause_s"])
        try:
            for wave in range(int(spec["session_rounds"])):
                if wave and pause > 0:
                    # pace the waves (wave_pause_s) so a short-session workload
                    # still overlaps the learner's train→publish cadence —
                    # preemption cuts the pause short
                    deadline = time.monotonic() + pause
                    while time.monotonic() < deadline:
                        if signals.preemption_requested() or self.server._error is not None:
                            return
                        time.sleep(min(0.05, pause))
                if signals.preemption_requested() or self.server._error is not None:
                    return
                wave_cfg = dotdict(dict(self.cfg))
                wave_cfg["seed"] = int(self.cfg.seed) + wave * 100
                self.results.extend(
                    run_env_sessions(
                        self.server,
                        wave_cfg,
                        sessions=int(spec["sessions"]),
                        max_session_steps=int(spec["max_session_steps"]),
                        log_dir=live_dir,
                    )
                )
        except Exception as exc:
            self.error = exc

    def shutdown(self, *, preempted: bool) -> Dict[str, Any]:
        """Ordered role teardown: reloader → ingest (drain + ship) → final
        ingest accounting → writer EOS → server close. Returns the role's
        accounting for the gang's ``live`` shutdown event."""
        if self.reloader is not None:
            self.reloader.stop()
        self.ingest.close()
        snapshot = self.ingest.telemetry_snapshot()
        self.telemetry.emit_event(
            "ingest", role="actor", rank=self.rank, **snapshot, **self.writer.telemetry_snapshot()
        )
        try:
            self.writer.close(preempted=preempted)
        except Exception:
            pass  # a dead learner must not block the serve teardown
        self.server.close(clean_exit=self.server._error is None)
        return {
            "rank": self.rank,
            "sessions": len(self.results),
            "session_errors": sum(1 for r in self.results if r.get("error")),
            "reloads": int(self.server.reloads),
            "weight_version": int(self.server.weight_version),
            **snapshot,
        }


class _LiveAttempt:
    """One attempt of the whole gang: a fresh in-process service plane, a fresh
    learner thread and fresh serve roles; the supervisor runs several of these
    against the same live dir (per-attempt stream identity)."""

    def __init__(
        self, cfg: Any, lcfg: Any, fabric: Any, live_dir: str, spec: Dict[str, Any], attempt: int
    ) -> None:
        self.cfg = cfg
        self.lcfg = lcfg
        self.fabric = fabric
        self.live_dir = live_dir
        self.spec = spec
        self.attempt = int(attempt)

    def run(self, emit_live) -> Dict[str, Any]:
        from sheeprl_tpu.config import instantiate, set_by_path
        from sheeprl_tpu.data.service import (
            clear_local_service_plane,
            install_local_service_plane,
            service_options,
        )
        from sheeprl_tpu.resilience import signals
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        spec = self.spec
        servers = int(spec["servers"])
        kv, ns = install_local_service_plane()
        set_by_path(self.lcfg, "metric.telemetry.attempt", self.attempt, create=True)
        opts = service_options(self.lcfg)
        layout = {
            "nprocs": servers + 1,
            "actors": servers,
            "learners": 1,
            "actor_ranks": tuple(range(servers)),
            "learner_ranks": (servers,),
            "leader": servers,
        }

        roles: List[_LiveRole] = []
        learner_error: List[BaseException] = []
        lthread: Optional[threading.Thread] = None
        watcher: Optional[threading.Thread] = None
        stop_watch = threading.Event()
        drained = threading.Event()
        preempted = False
        try:
            # the learner's Fabric comes from the checkpoint config
            # (instantiate resolves its CheckpointCallback — the learner's
            # checkpoint path runs through fabric.call("on_checkpoint_player"))
            lfabric = instantiate(
                self.lcfg.fabric,
                checkpoint_backend=str(self.lcfg.checkpoint.get("backend", "pickle")),
                checkpoint_async=bool(self.lcfg.checkpoint.get("async_save", False)),
            )
            lfabric.local_mesh = True
            lfabric._setup()

            def _learn() -> None:
                from sheeprl_tpu.algos.sac.sac_decoupled import _service_learner

                try:
                    _service_learner(lfabric, self.lcfg, layout)
                except BaseException as exc:  # noqa: BLE001 — the gang must see it
                    learner_error.append(exc)

            lthread = threading.Thread(target=_learn, name="sheeprl-live-learner", daemon=True)
            lthread.start()

            state = load_checkpoint(self.cfg.checkpoint_path)
            for rank in range(servers):
                roles.append(
                    _LiveRole(
                        rank,
                        self.cfg,
                        self.fabric,
                        state,
                        self.live_dir,
                        spec,
                        kv=kv,
                        ns=ns,
                        opts=opts,
                        attempt=self.attempt,
                    )
                )
            del state
            for role in roles:
                role.start()
            emit_live(
                "live",
                status="start",
                servers=servers,
                sessions=int(spec["sessions"]),
                session_rounds=int(spec["session_rounds"]),
                slots=int(self.cfg.serve.slots),
                explore_slots=int(roles[0].server.explore_slots) if roles else 0,
                checkpoint_path=str(self.cfg.checkpoint_path),
                namespace=ns,
            )

            grace = float(spec["drain_grace_s"])

            def _watch() -> None:
                while not stop_watch.wait(0.2):
                    if signals.preemption_requested() and not drained.is_set():
                        drained.set()
                        print(
                            f"[sheeprl-live] preemption requested: draining {len(roles)} "
                            f"server(s) (grace {grace:.0f}s) — admissions stopped, "
                            "in-flight sessions finishing",
                            file=sys.stderr,
                            flush=True,
                        )
                        drains = [
                            threading.Thread(
                                target=role.server.drain,
                                args=(grace,),
                                kwargs={"clean_exit": True},
                                daemon=True,
                            )
                            for role in roles
                        ]
                        for t in drains:
                            t.start()
                        for t in drains:
                            t.join(timeout=grace + 30.0)
                        return

            watcher = threading.Thread(target=_watch, name="sheeprl-live-watch", daemon=True)
            watcher.start()

            drivers = [
                threading.Thread(
                    target=role.drive,
                    args=(spec, self.live_dir),
                    name=f"sheeprl-live-drive{role.rank}",
                    daemon=True,
                )
                for role in roles
            ]
            for t in drivers:
                t.start()
            for t in drivers:
                t.join()
        finally:
            stop_watch.set()
            preempted = signals.preemption_requested()
            if preempted and watcher is not None:
                # the watcher owns the drain — let it finish (grace-bounded)
                watcher.join(timeout=float(spec["drain_grace_s"]) + 60.0)
            role_info = []
            for role in roles:
                try:
                    role_info.append(role.shutdown(preempted=preempted))
                except Exception as exc:
                    if not isinstance(role.error, BaseException):
                        role.error = exc
            if lthread is not None:
                lthread.join(timeout=_LEARNER_JOIN_S)
                if lthread.is_alive():
                    learner_error.append(
                        TimeoutError(
                            f"learner did not exit within {_LEARNER_JOIN_S:.0f}s of serve shutdown"
                        )
                    )
            clear_local_service_plane()

        error: Optional[BaseException] = None
        for role in roles:
            if role.server._error is not None:
                error = role.server._error
                break
            if role.error is not None:
                error = role.error
                break
        if error is None and learner_error:
            error = learner_error[0]
        results = [r for role in roles for r in role.results]
        info = {
            "results": results,
            "preempted": preempted,
            "error": error,
            "sessions_lost": sum(1 for r in results if r.get("error")),
            "reloads": sum(int(r.get("reloads") or 0) for r in role_info),
            "roles": role_info,
        }
        emit_live(
            "live",
            status="shutdown",
            preempted=bool(preempted),
            error=repr(error)[:500] if error is not None else None,
            sessions=len(results),
            sessions_lost=int(info["sessions_lost"]),
            reloads=int(info["reloads"]),
            trajectories_ingested=sum(
                int(r.get("trajectories_ingested") or 0) for r in role_info
            ),
            trajectories_dropped=sum(
                int(r.get("trajectories_dropped") or 0) for r in role_info
            ),
            trajectory_rows=sum(int(r.get("trajectory_rows") or 0) for r in role_info),
        )
        return info


def live_main(args: Optional[Sequence[str]] = None) -> int:
    """The ``live`` verb implementation (called by ``sheeprl_tpu.cli.live``)."""
    import sheeprl_tpu  # noqa: F401 — populate the serve registry

    from sheeprl_tpu.live.spec import load_live_spec, serve_overrides, write_marker
    from sheeprl_tpu.obs.jsonl import JsonlEventSink
    from sheeprl_tpu.parallel.fabric import Fabric
    from sheeprl_tpu.resilience import signals
    from sheeprl_tpu.resilience.restart_policy import RestartPolicy, run_restart_policy
    from sheeprl_tpu.serve.main import build_serve_cfg
    from sheeprl_tpu.utils.compile_cache import enable_compile_cache
    from sheeprl_tpu.utils.logger import set_run_dir

    argv = list(args if args is not None else sys.argv[1:])
    if not argv:
        print("usage: sheeprl.py live <spec.yaml> [key=value ...]", file=sys.stderr)
        return 2
    spec = load_live_spec(argv[0], argv[1:])
    cfg = build_serve_cfg(serve_overrides(spec))
    if not str(cfg.algo.name).startswith("sac"):
        print(
            f"[sheeprl-live] checkpoint algo {cfg.algo.name!r} has no service learner: "
            "the live flywheel currently trains SAC-family policies "
            "(the learner is sac_decoupled's buffer.backend=service learner)",
            file=sys.stderr,
        )
        return 2
    if spec["servers"] < 1 or spec["sessions"] < 1:
        print(
            "[sheeprl-live] nothing to drive: the spec needs servers >= 1 and "
            "sessions >= 1 (each server drives its sessions through its own slots)",
            file=sys.stderr,
        )
        return 2

    live_dir = spec["log_dir"] or _default_live_dir(spec)
    os.makedirs(live_dir, exist_ok=True)
    # every role's artifacts land under the live dir: the learner's
    # run_base_dir (checkpoints, memmap buffer) resolves to <live_dir>/learner
    set_run_dir(live_dir)
    streams = {"serve0": "telemetry.jsonl", "learner": "telemetry.learner.jsonl", "live": "telemetry.live.jsonl"}
    for k in range(1, spec["servers"]):
        streams[f"serve{k}"] = f"telemetry.serve{k}.jsonl"
    write_marker(live_dir, spec, streams)

    lcfg = _learner_cfg(cfg, spec, live_dir)

    enable_compile_cache()
    fabric = Fabric(
        devices=1,
        accelerator=cfg.fabric.get("accelerator", "auto"),
        precision=cfg.fabric.get("precision", "32-true"),
        checkpoint_backend=str((cfg.get("checkpoint") or {}).get("backend", "pickle")),
    )
    fabric._setup()

    # cooperative SIGTERM: the handler records (main thread), the drain watcher
    # and the learner's resilience poll act — one signal winds the WHOLE gang down
    handler_installed = signals.install_preemption_handler()

    print(
        f"[sheeprl-live] flywheel {spec['name']}: {spec['servers']} server(s) x "
        f"{cfg.serve.slots} slots from {cfg.checkpoint_path}, "
        f"{spec['sessions']} session(s)/server x {spec['session_rounds']} wave(s), "
        f"explore fraction {(cfg.serve.get('explore') or {}).get('fraction', 0.0)}, "
        f"telemetry at {live_dir}"
    )

    sink = JsonlEventSink(os.path.join(live_dir, "telemetry.live.jsonl"))
    sup_cfg = spec["supervisor"]
    state: Dict[str, Any] = {"info": None, "lost_total": 0}
    policy_obj = RestartPolicy.from_cfg(sup_cfg)
    # a preempted (SIGTERM-drained) gang EXITS 75 for the external supervisor —
    # restarting it in-process would undo the drain
    policy_obj.restart_on_preempt = False

    def emit(event: str, **fields: Any) -> None:
        fields.setdefault("attempt", policy_obj.attempt)
        sink.emit(event, **fields)

    def run_attempt(attempt: int):
        try:
            info = _LiveAttempt(cfg, lcfg, fabric, live_dir, spec, attempt).run(emit)
        except Exception as err:  # SystemExit/KeyboardInterrupt propagate
            info = {"results": [], "preempted": False, "error": err, "sessions_lost": 0}
        state["info"] = info
        if info["preempted"]:
            return "preempt", info
        if info["error"] is not None:
            state["lost_total"] += int(info["sessions_lost"])
            return "crash", info
        return "completed", info

    def restart_fields(attempt, outcome, info):
        return {
            "error": repr(info.get("error"))[:500] if info.get("error") else None,
            "sessions_lost": int(info.get("sessions_lost") or 0),
            "sessions_lost_total": int(state["lost_total"]),
        }

    def giveup_fields(info):
        return {
            "error": repr(info.get("error")) if info.get("error") else None,
            "sessions_lost_total": int(state["lost_total"]),
        }

    def on_giveup(outcome, info):
        return "gave_up"

    try:
        if not bool(sup_cfg.get("enabled")):
            outcome, info = run_attempt(0)
        else:
            run_restart_policy(
                policy_obj,
                run_attempt,
                emit,
                restart_fields=restart_fields,
                giveup_fields=giveup_fields,
                on_giveup=on_giveup,
            )
        return _verdict(state["info"])
    finally:
        sink.close()
        set_run_dir(None)
        if handler_installed:
            signals.uninstall_preemption_handler()


def _verdict(info: Optional[Dict[str, Any]]) -> int:
    """Map the final attempt's outcome onto the live exit-code taxonomy."""
    from sheeprl_tpu.resilience.signals import PREEMPTED_EXIT_CODE

    if info is None:
        return 1
    for r in info.get("roles") or []:
        print(
            f"[sheeprl-live] serve{r['rank']}: {r['sessions']} session(s) "
            f"({r['session_errors']} failed), {r['trajectories_ingested']} "
            f"trajectorie(s) ingested ({r['trajectories_dropped']} shed), "
            f"{r['reloads']} hot reload(s) to weight v{r['weight_version']}"
        )
    if info["preempted"]:
        print(
            "[sheeprl-live] gang drained after preemption request — clean exit "
            f"(code {PREEMPTED_EXIT_CODE})"
        )
        return PREEMPTED_EXIT_CODE
    if info["error"] is not None:
        print(f"[sheeprl-live] gang crashed: {info['error']!r}", file=sys.stderr)
        return 1
    return 1 if any(r.get("error") for r in info["results"]) else 0
