"""Closed-loop live RL: the serve→experience→learn→reload flywheel.

``python sheeprl.py live <spec>`` runs one supervised in-process gang where
serving slots double as actors: finished sessions feed an experience-service
learner whose published weights hot-reload into every server between ticks.
See :mod:`sheeprl_tpu.live.runner` for the gang anatomy and howto/live.md for
operation.
"""

from sheeprl_tpu.live.spec import LIVE_MARKER, load_live_spec, read_marker, write_marker

__all__ = ["LIVE_MARKER", "load_live_spec", "read_marker", "write_marker"]
